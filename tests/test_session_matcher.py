"""Per-vehicle session matcher: the carry-seam differential suite extended
to session-incremental decoding (docs/performance.md "The session matcher").

The bit-exact contract: a session's incremental answers equal the windowed
``match_trace`` path at every matched window boundary —

  * point-at-a-time == the W=1 carried-window chain (every point is a
    seam; the causal commit at each step is exactly the windowed carry
    machinery's seam commit),
  * chunk-at-a-time == the long-trace chunked path at the same seams,
  * a whole-trace step == the single-window batch decode,
  * rebuild-from-replay == the windowed decode of the replayed history,

wire- and CompactMatch-identical, for both viterbi kernels, interleaved
across many uuids, through store eviction, serialisation round trips and
the drain-time beam handoff.
"""

import numpy as np
import pytest

from reporter_tpu.matching import MatcherConfig, SegmentMatcher
from reporter_tpu.matching.session import (
    SessionEngine, SessionState, SessionStore,
)
from reporter_tpu.synth import TraceSynthesizer
from reporter_tpu.tiles.arrays import build_graph_arrays
from reporter_tpu.tiles.network import grid_city
from reporter_tpu.tiles.ubodt import build_ubodt

MO = {"mode": "auto", "report_levels": [0, 1], "transition_levels": [0, 1]}


@pytest.fixture(scope="module")
def setup():
    city = grid_city(rows=8, cols=8, spacing_m=150.0)
    arrays = build_graph_arrays(city, cell_size=100.0)
    ubodt = build_ubodt(arrays, delta=1500.0)
    return arrays, ubodt


def _matcher(setup, kernel="scan", **kw):
    arrays, ubodt = setup
    cfg = MatcherConfig(length_buckets=[16], session_buckets=[4, 16],
                        viterbi_kernel=kernel, **kw)
    return SegmentMatcher(arrays=arrays, ubodt=ubodt, config=cfg)


def _traces(arrays, b, t, seed=11, sigma=3.0):
    synth = TraceSynthesizer(arrays, seed=seed)
    return [s.trace for s in synth.batch(b, t, dt=5.0, sigma=sigma)]


def _engine(m, tail=512):
    store = SessionStore()
    return SessionEngine(m, store, tail_points=tail), store


def _stream(eng, tr, step=1, uuid=None):
    """Feed a trace through the engine in ``step``-point submits."""
    uuid = uuid or tr["uuid"]
    pts = tr["trace"]
    out = []
    for j in range(0, len(pts), step):
        out.extend(eng.match_many([
            {"uuid": uuid, "trace": pts[j:j + step], "match_options": MO}]))
    return out


def _session_records(store, uuid):
    s = store.peek(uuid)
    return (np.array([r[0] for r in s.records], np.int64),
            np.array([r[1] for r in s.records], np.float32),
            np.array([r[2] for r in s.records], bool))


def _windowed_records(m, tr):
    """The windowed batch path's CompactMatch for one trace (bucketed or
    long-trace chunked, whatever match_many would dispatch)."""
    n = len(tr["trace"])
    if n > m.cfg.length_buckets[-1]:
        handles = m._dispatch_long([tr], [0])
        _grp, (edge, offset, breaks), _tm = m._fetch_long(handles[0])
    else:
        px, py, tm, valid, _ = m._fill_rows([tr], [0], m._bucket_len(n))
        edge, offset, breaks = m._collect_batch(
            m._dispatch_batch(*m._pad_batch(px, py, tm, valid)))
    return (edge[0, :n].astype(np.int64),
            offset[0, :n].astype(np.float32), breaks[0, :n] != 0)


def _w1_chain_records(m, tr):
    """W=1 carried-window chain via the WINDOWED carry machinery
    (match_batch_carry) — the matched-boundary reference for
    point-at-a-time streaming."""
    import jax.numpy as jnp

    from reporter_tpu.ops.viterbi import (
        MatchParams, initial_carry_batch, match_batch_carry,
    )

    n = len(tr["trace"])
    px, py, tm, valid, _ = m._fill_rows([tr], [0], n)
    p = MatchParams.from_config(m.cfg)
    carry = initial_carry_batch(1, m.cfg.beam_k)
    E, O, B = [], [], []
    for t in range(n):
        cm, carry = match_batch_carry(
            m._dg, m._du, jnp.asarray(px[:, t:t + 1]),
            jnp.asarray(py[:, t:t + 1]), jnp.asarray(tm[:, t:t + 1]),
            jnp.asarray(valid[:, t:t + 1]), p, m.cfg.beam_k, carry,
            kernel=m._kernel_for(1))
        E.append(int(np.asarray(cm.edge)[0, 0]))
        O.append(np.float32(np.asarray(cm.offset)[0, 0]))
        B.append(bool(np.asarray(cm.breaks)[0, 0]))
    return np.array(E, np.int64), np.array(O, np.float32), np.array(B)


def _assert_records_equal(a, b, what=""):
    ae, ao, ab_ = a
    be, bo, bb = b
    assert np.array_equal(ae, be), (what, np.nonzero(ae != be))
    # offsets must agree BITWISE (f32), not approximately
    assert np.array_equal(ao.view(np.int32), bo.view(np.int32)), what
    assert np.array_equal(ab_, bb), what


# -- bit-exact differentials -------------------------------------------------


@pytest.mark.parametrize("kernel", [
    "scan", pytest.param("assoc", marks=pytest.mark.slow)])
def test_point_at_a_time_bitexact_vs_windowed_w1_chain(setup, kernel):
    """Streaming one point per step must reproduce the windowed carry
    machinery at W=1 seams bit-exactly — CompactMatch-identical."""
    arrays, _ = setup
    m = _matcher(setup, kernel)
    for tr in _traces(arrays, 3, 24):
        eng, store = _engine(m)
        _stream(eng, tr, step=1)
        _assert_records_equal(
            _session_records(store, tr["uuid"]), _w1_chain_records(m, tr),
            what=tr["uuid"])


@pytest.mark.parametrize("kernel", ["scan", "assoc"])
def test_whole_trace_step_bitexact_vs_windowed(setup, kernel):
    """A single session step covering the whole trace IS the windowed
    single-dispatch decode: records bit-identical, wire segments equal."""
    arrays, _ = setup
    m = _matcher(setup, kernel)
    for tr in _traces(arrays, 3, 14, seed=4):
        eng, store = _engine(m)
        out = _stream(eng, tr, step=len(tr["trace"]))
        _assert_records_equal(
            _session_records(store, tr["uuid"]), _windowed_records(m, tr),
            what=tr["uuid"])
        # wire-identical: the answer's segments equal the windowed match
        assert out[-1]["segments"] == m.match(tr)["segments"]


def test_chunk_steps_bitexact_vs_long_trace_path(setup):
    """Session steps at the long path's own window boundaries (W = the
    largest length bucket) reproduce the chunked windowed match_trace
    decode bit-exactly, and the final accumulated answer is
    wire-identical to match()."""
    arrays, _ = setup
    m = _matcher(setup)
    W = m.cfg.length_buckets[-1]
    for tr in _traces(arrays, 2, 3 * W, seed=9):
        eng, store = _engine(m)
        out = _stream(eng, tr, step=W)
        _assert_records_equal(
            _session_records(store, tr["uuid"]), _windowed_records(m, tr),
            what=tr["uuid"])
        assert out[-1]["segments"] == m.match(tr)["segments"]


def test_interleaved_sessions_match_isolated_sessions(setup):
    """Many vehicles stepping through SHARED dispatches (one [B, W]
    program folding several sessions) decode exactly as each would
    alone — batch isolation, with mixed step sizes and (for one vehicle)
    two submits folded into a single engine batch.  The isolated
    reference submits the SAME per-batch pattern one vehicle at a time,
    so the decode boundaries match and the only variable is who shares
    the dispatch."""
    arrays, _ = setup
    m = _matcher(setup)
    traces = _traces(arrays, 4, 18, seed=21)
    steps = {tr["uuid"]: s for tr, s in zip(traces, (1, 2, 3, 1))}

    # per-vehicle submission pattern: a list of batches, each batch a
    # list of point-slices (vehicle 0 sends two consecutive 1-point
    # submits per batch — they legitimately fold into one window)
    plan = {}
    for vi, tr in enumerate(traces):
        u, s = tr["uuid"], steps[tr["uuid"]]
        batches, c = [], 0
        while c < len(tr["trace"]):
            subs = [tr["trace"][c:c + s]]
            c += s
            if vi == 0 and c < len(tr["trace"]):
                subs.append(tr["trace"][c:c + s])
                c += s
            batches.append(subs)
        plan[u] = batches

    def submit(eng, u, subs):
        eng.match_many([{"uuid": u, "trace": pts, "match_options": MO}
                        for pts in subs if pts])

    # isolated reference: one vehicle at a time, same batch pattern
    ref = {}
    for tr in traces:
        eng, store = _engine(m)
        for subs in plan[tr["uuid"]]:
            submit(eng, tr["uuid"], subs)
        ref[tr["uuid"]] = _session_records(store, tr["uuid"])

    # interleaved: round k merges every vehicle's k-th batch into ONE
    # engine batch (one shared [B, W] dispatch per bucket)
    eng, store = _engine(m)
    rounds = max(len(b) for b in plan.values())
    for k in range(rounds):
        batch = []
        for tr in traces:
            batches = plan[tr["uuid"]]
            if k < len(batches):
                batch.extend(
                    {"uuid": tr["uuid"], "trace": pts, "match_options": MO}
                    for pts in batches[k] if pts)
        eng.match_many(batch)
    for tr in traces:
        _assert_records_equal(_session_records(store, tr["uuid"]),
                              ref[tr["uuid"]], what=tr["uuid"])


def test_rebuild_from_replay_bitexact_vs_windowed(setup):
    """A beam-less session (replay-only handoff payload, or a degraded
    window) rebuilds by re-matching its replay buffer: with the replay
    covering the full history, the rebuilt records ARE the windowed
    decode of the whole trace — bit-exact."""
    arrays, _ = setup
    m = _matcher(setup)
    tr = _traces(arrays, 1, 12, seed=33)[0]
    eng, store = _engine(m)
    _stream(eng, tr, step=1, uuid="veh-r")

    # serialise, strip the beam (the replay-only handoff), re-import
    wire = store.peek("veh-r").to_wire()
    wire["carry"] = None
    store2 = SessionStore()
    assert store2.import_wire([wire]) == {
        "imported": 1, "merged": 0, "skipped": 0, "rebuild_pending": 1,
        "imported_uuids": ["veh-r"]}
    eng2 = SessionEngine(m, store2, tail_points=512)

    # next point triggers the rebuild; the session's records become the
    # windowed decode of ALL points seen so far
    extra = dict(tr["trace"][-1])
    extra = {"lat": extra["lat"], "lon": extra["lon"],
             "time": extra["time"] + 5.0}
    eng2.match_many([{"uuid": "veh-r", "trace": [extra],
                      "match_options": MO}])
    s2 = store2.peek("veh-r")
    assert s2.rebuild_pending is False
    full = {"uuid": "veh-r", "trace": tr["trace"] + [extra]}
    _assert_records_equal(_session_records(store2, "veh-r"),
                          _windowed_records(m, full))


def test_long_replay_rebuild_chains_warmed_shapes(setup):
    """An over-bucket rebuild (replay longer than the largest session
    bucket) must CHAIN through the largest warmed [B, W] session shape —
    no new compiled shapes — and its decode equals the windowed
    long-trace path's bit-exactly (carry seams at W boundaries)."""
    arrays, _ = setup
    m = _matcher(setup)
    tr = _traces(arrays, 1, 40, seed=27)[0]
    eng, store = _engine(m)
    _stream(eng, tr, step=1, uuid="veh-lr")
    wire = store.peek("veh-lr").to_wire()
    wire["carry"] = None  # replay-only handoff: forces the rebuild
    store2 = SessionStore()
    store2.import_wire([wire])
    eng2 = SessionEngine(m, store2, tail_points=512)
    shapes_before = set(m._compiled_shapes)
    extra = dict(tr["trace"][-1])
    extra = {"lat": extra["lat"], "lon": extra["lon"],
             "time": extra["time"] + 5.0}
    eng2.match_many([{"uuid": "veh-lr", "trace": [extra],
                      "match_options": MO}])
    w_max = m.cfg.session_buckets[-1]
    new_shapes = set(m._compiled_shapes) - shapes_before
    assert all(k[-1] <= w_max for k in new_shapes
               if k[0] == "session"), (
        "the rebuild compiled an over-bucket session shape: %r" % new_shapes)
    full = {"uuid": "veh-lr", "trace": tr["trace"] + [extra]}
    _assert_records_equal(_session_records(store2, "veh-lr"),
                          _windowed_records(m, full))


def test_wire_roundtrip_continues_bitexact(setup):
    """Export -> import (the drain-time beam handoff) -> continue: the
    inheriting matcher's decode equals the uninterrupted one bit-exactly
    (the carry travels as exact f32)."""
    arrays, _ = setup
    m1 = _matcher(setup)
    m2 = _matcher(setup)  # the inheriting replica's engine
    tr = _traces(arrays, 1, 20, seed=5)[0]
    cut = 11

    # uninterrupted reference
    eng, store = _engine(m1)
    _stream(eng, tr, step=1, uuid="veh-h")
    ref = _session_records(store, "veh-h")

    # interrupted at `cut`: serialise, hand off, continue elsewhere
    eng1, store1 = _engine(m1)
    head = {"uuid": "veh-h", "trace": tr["trace"][:cut]}
    _stream(eng1, head, step=1, uuid="veh-h")
    wires = store1.export_all()
    assert len(wires) == 1 and wires[0]["carry"] is not None
    # JSON round trip like the real handoff POST
    import json

    wires = json.loads(json.dumps(wires))
    store2 = SessionStore()
    assert store2.import_wire(wires)["imported"] == 1
    eng2 = SessionEngine(m2, store2, tail_points=512)
    tail = {"uuid": "veh-h", "trace": tr["trace"][cut:]}
    _stream(eng2, tail, step=1, uuid="veh-h")
    _assert_records_equal(_session_records(store2, "veh-h"), ref)
    # the zero-lost ledger rides the wire: points_total accumulates
    # ACROSS the handoff, so the fleet-wide sum still counts every point
    # exactly once
    s2 = store2.peek("veh-h")
    assert s2.points_total == len(tr["trace"])


def test_import_merges_into_live_session(setup):
    """A uuid already live locally MERGES with the import (the racing
    re-dispatch opened a fresh session before the handoff landed): the
    imported replay prepends, the decode is flagged for a rebuild over
    the combined history, and the points ledger absorbs the imported
    count — zero lost, zero duplicated."""
    arrays, _ = setup
    m = _matcher(setup)
    tr = _traces(arrays, 1, 12, seed=6)[0]
    cut = 8
    # the handed-off history (pre-drain decode, cut points)
    eng1, store1 = _engine(m)
    _stream(eng1, {"uuid": "x", "trace": tr["trace"][:cut]}, step=1,
            uuid="veh-l")
    wire = store1.export_all()[0]
    # the race loser: a fresh session that already absorbed 2 points
    eng, store = _engine(m)
    _stream(eng, {"uuid": "x", "trace": tr["trace"][cut:cut + 2]}, step=1,
            uuid="veh-l")
    live = store.peek("veh-l")
    res = store.import_wire([wire])
    assert res["merged"] == 1 and res["imported"] == 0
    assert res["imported_uuids"] == ["veh-l"]
    assert store.peek("veh-l") is live
    assert live.points_total == cut + 2  # ledger absorbed, nothing lost
    assert live.rebuild_pending
    # the next step rebuilds over the combined history: bit-exact vs the
    # windowed decode of every point seen so far
    eng.match_many([{"uuid": "veh-l", "trace": [tr["trace"][cut + 2]],
                     "match_options": MO}])
    full = {"uuid": "veh-l", "trace": tr["trace"][:cut + 3]}
    _assert_records_equal(_session_records(store, "veh-l"),
                          _windowed_records(m, full))
    assert store.peek("veh-l").points_total == cut + 3
    # an empty payload (no replay) is a pure ledger merge: no rebuild
    res = store.import_wire([SessionState("veh-l", 0.0).to_wire()])
    assert res["merged"] == 1
    assert store.peek("veh-l").rebuild_pending is False


def test_store_ttl_and_lru_eviction(setup):
    arrays, _ = setup
    m = _matcher(setup)
    store = SessionStore(max_sessions=2, ttl_s=3600.0)
    eng = SessionEngine(m, store, tail_points=64)
    traces = _traces(arrays, 3, 4, seed=8)
    for i, tr in enumerate(traces):
        _stream(eng, tr, step=1, uuid="veh-%d" % i)
    # LRU bound: veh-0 (least recently stepped) was evicted
    assert len(store) == 2
    assert store.peek("veh-0") is None
    assert store.peek("veh-2") is not None
    # TTL: an ancient session expires on the next access sweep
    store.peek("veh-2").last_used -= 7200.0
    store.get_or_open("veh-9", t0=1.0)
    assert store.peek("veh-2") is None


def test_params_change_reopens_session(setup):
    """A changed per-request sigma_z invalidates the carried scores: the
    session restarts under the new params key instead of mixing scales."""
    arrays, _ = setup
    m = _matcher(setup)
    eng, store = _engine(m)
    tr = _traces(arrays, 1, 6, seed=13)[0]
    _stream(eng, {"uuid": "veh-p", "trace": tr["trace"][:3]}, step=1,
            uuid="veh-p")
    s1 = store.peek("veh-p")
    assert s1.pkey == ()
    eng.match_many([{"uuid": "veh-p", "trace": [tr["trace"][3]],
                     "match_options": dict(MO, sigma_z=9.0)}])
    s2 = store.peek("veh-p")
    assert s2 is not s1 and s2.pkey != ()
    assert s2.points_total == 1


# -- service-level streaming (the wire) --------------------------------------


def test_streaming_report_wire_matches_windowed(setup):
    """The streaming POST /report path: per-point answers carry the
    session block, and once the session has consumed the whole trace the
    answer is wire-identical to the windowed /report of that trace."""
    import json

    from reporter_tpu.serve.service import ReporterService

    arrays, _ = setup
    m = _matcher(setup, session_tail_points=512)
    svc = ReporterService(m, max_wait_ms=1.0, session_wait_ms=1.0)
    tr = _traces(arrays, 1, 14, seed=17)[0]
    W = len(tr["trace"])

    code, ref = svc.handle_report(
        {"uuid": "veh-w", "trace": tr["trace"], "match_options": MO})
    assert code == 200

    code, out = svc.handle_report(
        {"uuid": "veh-s", "stream": True, "trace": tr["trace"],
         "match_options": MO})
    assert code == 200
    sess = out.pop("session")
    assert sess["points"] == W and sess["points_total"] == W
    assert sess["seq"] == 1 and sess["tail_points"] == W
    # byte-identical wire payload (json round trip normalises floats)
    assert json.loads(json.dumps(out)) == json.loads(json.dumps(ref))

    # point-at-a-time: every answer 200 with a growing session block and
    # the route classified under report_stream for the SLO engine
    from reporter_tpu.obs import slo as obs_slo

    for i, p in enumerate(tr["trace"]):
        code, out = svc.handle_report(
            {"uuid": "veh-s2", "stream": True, "trace": [p],
             "match_options": MO})
        assert code == 200, out
        assert out["session"]["seq"] == i + 1
        assert out["session"]["points_total"] == i + 1
    rep = obs_slo.engine().report()
    assert "report_stream" in rep["routes"]
    assert rep["routes"]["report_stream"]["good"] >= W + 1

    # single-point streaming is valid; single-point WINDOWED stays 400
    code, out = svc.handle_report(
        {"uuid": "veh-bad", "trace": [tr["trace"][0]],
         "match_options": MO})
    assert code == 400


def test_sessions_endpoint_export_import(setup):
    """GET /sessions (+?export=1) and POST import through the service
    handlers — the surface the router's beam handoff drives."""
    from reporter_tpu.serve.service import ReporterService

    arrays, _ = setup
    m = _matcher(setup)
    svc = ReporterService(m, max_wait_ms=1.0, session_wait_ms=1.0)
    tr = _traces(arrays, 1, 6, seed=19)[0]
    for p in tr["trace"]:
        code, _out = svc.handle_report(
            {"uuid": "veh-e", "stream": True, "trace": [p],
             "match_options": MO})
        assert code == 200
    code, out = svc.handle_sessions({})
    assert code == 200 and out["sessions"] == 1
    assert out["points_total"] == len(tr["trace"])
    code, out = svc.handle_sessions({"export": ["1"]})
    assert code == 200 and len(out["sessions"]) == 1
    code, one = svc.handle_sessions({"uuid": ["veh-e"]})
    assert code == 200 and one["points_total"] == len(tr["trace"])
    code, _ = svc.handle_sessions({"uuid": ["ghost"]})
    assert code == 404

    # import into a second service (the inheriting replica)
    svc2 = ReporterService(_matcher(setup), max_wait_ms=1.0,
                           session_wait_ms=1.0)
    code, res = svc2.handle_sessions({}, {"sessions": out["sessions"]})
    assert code == 200 and res["imported"] == 1
    code, res = svc2.handle_sessions({}, {"sessions": "nope"})
    assert code == 400


def test_session_metrics_and_dispatch_cohort(setup):
    """The session plane is metrics-instrumented: lifecycle counters,
    folded-point counter, and the session dispatch cohort."""
    from reporter_tpu.obs import metrics as obs

    def fam(name):
        return obs.REGISTRY.snapshot().get(name, {"samples": []})["samples"]

    arrays, _ = setup
    m = _matcher(setup)
    before_opened = sum(v for lv, v in fam("reporter_sessions_total")
                        if lv == ["opened"])
    before_pts = sum(v for _lv, v in fam("reporter_session_points_total"))
    before_disp = sum(v for lv, v in fam("reporter_dispatch_cohort_total")
                      if lv == ["session", "step"])
    eng, store = _engine(m)
    tr = _traces(arrays, 1, 5, seed=23)[0]
    _stream(eng, tr, step=1, uuid="veh-m")
    snap_opened = sum(v for lv, v in fam("reporter_sessions_total")
                      if lv == ["opened"])
    assert snap_opened == before_opened + 1
    assert sum(v for _lv, v in fam("reporter_session_points_total")) \
        == before_pts + len(tr["trace"])
    assert sum(v for lv, v in fam("reporter_dispatch_cohort_total")
               if lv == ["session", "step"]) \
        == before_disp + len(tr["trace"])


# -- hedging-aware idempotency (docs/serving-fleet.md "Beam handoff") -------


def test_hedged_duplicate_point_commits_once(setup):
    """The same raw point delivered twice (a hedged "stream": true
    request that landed on two replicas, or a client retry) commits
    ONCE: the ledger counts it once, the duplicate still gets a full
    answer from the accumulated tail, and the decode stays bit-exact
    with a clean single-delivery stream."""
    from reporter_tpu.matching.session import C_SESSION_DEDUP

    arrays, _ = setup
    m = _matcher(setup)
    eng, store = _engine(m)
    tr = _traces(arrays, 1, 6, seed=31)[0]
    pts = tr["trace"]
    d0 = C_SESSION_DEDUP.value
    a1 = eng.match_many([{"uuid": "hedge", "trace": pts[:1],
                          "match_options": MO}])[0]
    a2 = eng.match_many([{"uuid": "hedge", "trace": pts[:1],
                          "match_options": MO}])[0]
    s = store.peek("hedge")
    assert s.points_total == 1 and s.seq == 1
    assert C_SESSION_DEDUP.value == d0 + 1
    assert a2["_stream"]["session"].get("deduped") is True
    assert a2["_stream"]["session"]["points"] == 0
    assert a2["segments"] == a1["segments"]
    # the stream continues unperturbed: feed the rest, compare the
    # decode against a clean engine that never saw the duplicate
    for j in range(1, len(pts)):
        eng.match_many([{"uuid": "hedge", "trace": pts[j:j + 1],
                         "match_options": MO}])
    eng2, store2 = _engine(m)
    _stream(eng2, tr, step=1, uuid="clean")
    _assert_records_equal(_session_records(store, "hedge"),
                          _session_records(store2, "clean"),
                          "post-dedup stream vs clean stream")
    assert store.peek("hedge").points_total == len(pts)


def test_duplicate_within_one_batch_commits_once(setup):
    """Two submits of the same point co-batched in ONE micro-batch (the
    tightest hedge race) fold to one committed copy; both get answers."""
    arrays, _ = setup
    m = _matcher(setup)
    eng, store = _engine(m)
    tr = _traces(arrays, 1, 4, seed=37)[0]
    p = tr["trace"][:1]
    out = eng.match_many([
        {"uuid": "race", "trace": p, "match_options": MO},
        {"uuid": "race", "trace": p, "match_options": MO},
    ])
    assert len(out) == 2 and all(o is not None for o in out)
    assert store.peek("race").points_total == 1


def test_partial_duplicate_commits_only_fresh(setup):
    """A retry carrying one already-committed point plus one new point
    commits only the new one — and the decode equals the clean stream."""
    arrays, _ = setup
    m = _matcher(setup)
    eng, store = _engine(m)
    tr = _traces(arrays, 1, 5, seed=41)[0]
    pts = tr["trace"]
    eng.match_many([{"uuid": "part", "trace": pts[:1],
                     "match_options": MO}])
    out = eng.match_many([{"uuid": "part", "trace": pts[:2],
                          "match_options": MO}])[0]
    s = store.peek("part")
    assert s.points_total == 2
    assert out["_stream"]["session"]["points"] == 1
    for j in range(2, len(pts)):
        eng.match_many([{"uuid": "part", "trace": pts[j:j + 1],
                         "match_options": MO}])
    eng2, store2 = _engine(m)
    _stream(eng2, tr, step=1, uuid="clean2")
    _assert_records_equal(_session_records(store, "part"),
                          _session_records(store2, "clean2"),
                          "partial-duplicate stream vs clean stream")


def test_service_level_hedge_duplicate(setup):
    """Chaos-shaped end to end: the SAME streaming body served twice by
    the real service (what a hedge loser's late landing or a client
    retry looks like replica-side) answers 200 both times with ONE
    ledger entry."""
    from reporter_tpu.serve.service import ReporterService

    arrays, _ = setup
    m = _matcher(setup)
    svc = ReporterService(m, max_wait_ms=1.0, session_wait_ms=1.0)
    tr = _traces(arrays, 1, 4, seed=43)[0]
    body = {"uuid": "veh-hh", "stream": True,
            "trace": tr["trace"][:1], "match_options": MO}
    code1, out1 = svc.handle_report(dict(body))
    code2, out2 = svc.handle_report(dict(body))
    assert code1 == 200 and code2 == 200
    assert out2["session"].get("deduped") is True
    assert svc.session_store.peek("veh-hh").points_total == 1
    # degraded-mode parity: the dedup also guards the CPU-oracle path
    n0 = svc.session_store.peek("veh-hh").points_total
    svc.session_engine.degraded_step(m, dict(body))
    assert svc.session_store.peek("veh-hh").points_total == n0
