"""The packed host<->device transport (ops/viterbi.pack_inputs /
pack_compact and their inverses).

Batches cross the device boundary as ONE [4, B, T] f32 array and results
come back as ONE [3, B, T] i32 array because every crossing pays a fixed
dispatch/sync cost (measured ~73 ms per sync on the tunneled bench chip —
the r03 unpacked convention of 4 puts + 3 fetches tripled single-trace
latency).  These tests pin the roundtrip semantics the matcher and bench
both rely on.
"""

import numpy as np
import pytest

from reporter_tpu.matching import MatcherConfig, SegmentMatcher
from reporter_tpu.tiles.arrays import build_graph_arrays
from reporter_tpu.tiles.network import grid_city
from reporter_tpu.tiles.ubodt import build_ubodt


@pytest.fixture(scope="module")
def arrays():
    return build_graph_arrays(grid_city(rows=5, cols=5, spacing_m=150.0), cell_size=100.0)


@pytest.fixture(scope="module")
def ubodt(arrays):
    return build_ubodt(arrays, delta=2000.0)


def test_pack_inputs_roundtrip():
    from reporter_tpu.ops.viterbi import pack_inputs, unpack_inputs

    rng = np.random.default_rng(3)
    px = rng.normal(size=(5, 7)).astype(np.float32)
    py = rng.normal(size=(5, 7)).astype(np.float32)
    tm = rng.uniform(0, 1e4, size=(5, 7)).astype(np.float32)
    valid = rng.integers(0, 2, size=(5, 7)).astype(bool)

    xin = pack_inputs(px, py, tm, valid)
    assert xin.shape == (4, 5, 7) and xin.dtype == np.float32

    ux, uy, ut, uv = unpack_inputs(xin)  # works on numpy too
    np.testing.assert_array_equal(np.asarray(ux), px)
    np.testing.assert_array_equal(np.asarray(uy), py)
    np.testing.assert_array_equal(np.asarray(ut), tm)
    np.testing.assert_array_equal(np.asarray(uv), valid)


def test_pack_compact_roundtrip_preserves_float_payload():
    import jax.numpy as jnp

    from reporter_tpu.ops.viterbi import CompactMatch, pack_compact, unpack_compact

    rng = np.random.default_rng(4)
    edge = rng.integers(-1, 1 << 30, size=(3, 6)).astype(np.int32)
    # offsets include negatives, denormal-ish smalls, and exact values that
    # must survive bit-exactly through the i32 bitcast
    offset = np.array([
        [0.0, -0.0, 1.5, 3.1415927, 1e-38, 2.5e4],
        [7.25, -13.5, 0.1, 1e30, -1e-30, 5.0],
        [123.456, 0.333, 9.75, -2.0, 6.1e-5, 8e7],
    ], np.float32)
    breaks = rng.integers(0, 2, size=(3, 6)).astype(bool)

    packed = pack_compact(CompactMatch(
        edge=jnp.asarray(edge), offset=jnp.asarray(offset), breaks=jnp.asarray(breaks)))
    assert packed.shape == (3, 3, 6) and packed.dtype == jnp.int32

    e, o, b = unpack_compact(np.asarray(packed))
    np.testing.assert_array_equal(e, edge)
    assert o.dtype == np.float32
    np.testing.assert_array_equal(o.view(np.int32), offset.view(np.int32))  # bit-exact
    np.testing.assert_array_equal(b, breaks)


def _mk_trace(arrays, uuid, n, seed=0, jitter=3.0):
    rng = np.random.default_rng(seed)
    ax = float(arrays.node_x[arrays.edge_from[0]])
    ay = float(arrays.node_y[arrays.edge_from[0]])
    bx = float(arrays.node_x[arrays.edge_to[0]])
    by = float(arrays.node_y[arrays.edge_to[0]])
    xs = np.linspace(ax, bx, n) + rng.normal(0, jitter, n)
    ys = np.linspace(ay, by, n) + rng.normal(0, jitter, n)
    lat, lon = arrays.proj.to_latlon(xs, ys)
    return {"uuid": uuid, "trace": [
        {"lat": float(a), "lon": float(o), "time": 1000.0 + 5.0 * i}
        for i, (a, o) in enumerate(zip(lat, lon))]}


def test_matcher_output_unchanged_by_wave_size(arrays, ubodt, monkeypatch):
    """Long traces must produce identical results whether chunk outputs are
    fetched in one wave or many (MAX_DEFERRED_CHUNKS bounds device memory,
    never semantics)."""
    import reporter_tpu.matching.matcher as mm

    m = SegmentMatcher(arrays=arrays, ubodt=ubodt, config=MatcherConfig())
    trace = _mk_trace(arrays, "wave", 1400, seed=11)
    ref = m.match(trace)
    assert ref["segments"]
    monkeypatch.setattr(mm, "MAX_DEFERRED_CHUNKS", 1)
    assert m.match(trace) == ref
    monkeypatch.setattr(mm, "MAX_DEFERRED_CHUNKS", 2)
    assert m.match(trace) == ref


@pytest.mark.parametrize("kernel", ["scan", "assoc"])
def test_precompute_chain_composition_bit_identical(arrays, ubodt, kernel):
    """precompute_batch_packed + chain_batch_carry_packed (the hoisted
    long-trace program pair) must equal match_batch_carry_packed (the fused
    legacy program) BIT-exactly: packed outputs and every carry leaf, and
    again on a second chunk fed the first chunk's carry.  This is the
    ops-level contract the matcher-level differential
    (tests/test_fuzz_differential.py) rides on."""
    import functools

    import jax
    import jax.numpy as jnp

    from reporter_tpu.ops.viterbi import (
        MatchParams, chain_batch_carry_packed, initial_carry_batch,
        match_batch_carry_packed, pack_inputs, precompute_batch_packed,
    )

    cfg = MatcherConfig()
    p = MatchParams.from_config(cfg)
    k = cfg.beam_k
    dg, du = arrays.to_device(), ubodt.to_device()

    rng = np.random.default_rng(9)
    B, T = 4, 20
    px = rng.uniform(arrays.node_x.min(), arrays.node_x.max(),
                     (B, T)).astype(np.float32)
    py = rng.uniform(arrays.node_y.min(), arrays.node_y.max(),
                     (B, T)).astype(np.float32)
    tm = np.tile(np.arange(T, dtype=np.float32) * 5.0, (B, 1))
    valid = np.ones((B, T), bool)
    valid[2, 7:] = False  # padded tail mid-batch
    valid[3, :] = False  # all-pad row
    xin = jnp.asarray(pack_inputs(px, py, tm, valid))

    fused = jax.jit(functools.partial(match_batch_carry_packed, kernel=kernel),
                    static_argnums=(4,))
    jpre = jax.jit(precompute_batch_packed, static_argnums=(4,))
    jchain = jax.jit(functools.partial(chain_batch_carry_packed, kernel=kernel),
                     static_argnums=(5,))

    carry_f = carry_s = initial_carry_batch(B, k)
    pre = jpre(dg, du, xin, p, k)
    for _chunk in range(2):  # second round exercises an ACTIVE carry seam
        out_f, carry_f = fused(dg, du, xin, p, k, carry_f)
        out_s, carry_s = jchain(dg, du, pre, xin, p, k, carry_s)
        np.testing.assert_array_equal(np.asarray(out_f), np.asarray(out_s))
        for a, b in zip(jax.tree_util.tree_leaves(carry_f),
                        jax.tree_util.tree_leaves(carry_s)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_matcher_jax_vs_cpu_after_packing(arrays, ubodt):
    """The packed transport must not perturb the device/oracle diffability
    contract (segment-for-segment identical on clean traces)."""
    cfg = MatcherConfig()
    mj = SegmentMatcher(arrays=arrays, ubodt=ubodt, config=cfg)
    mc = SegmentMatcher(arrays=arrays, ubodt=ubodt, config=cfg, backend="cpu")
    traces = [_mk_trace(arrays, "t%d" % i, 12 + 9 * i, seed=i) for i in range(4)]
    out_j = mj.match_many(traces)
    out_c = mc.match_many(traces)
    ids = lambda r: [s.get("segment_id") for s in r["segments"]]
    assert [ids(r) for r in out_j] == [ids(r) for r in out_c]
