"""Match-quality observability plane (docs/match-quality.md): shadow-
oracle sampling, kernel confidence diagnostics, per-request
match_options parity, the agreement SLO objective + drift alerting, the
quality gate, and the ≤5% p99 sampling-overhead bound."""

import json
import time

import numpy as np
import pytest

from reporter_tpu import faults
from reporter_tpu.matching import MatcherConfig, SegmentMatcher
from reporter_tpu.obs import quality as obs_quality
from reporter_tpu.obs import slo as obs_slo
from reporter_tpu.obs.quality import QualityEngine, gap_bucket, len_bucket
from reporter_tpu.obs.slo import Objective, SLOEngine
from reporter_tpu.tiles.arrays import build_graph_arrays
from reporter_tpu.tiles.network import grid_city
from reporter_tpu.tiles.ubodt import build_ubodt


@pytest.fixture(scope="module")
def arrays():
    return build_graph_arrays(grid_city(rows=5, cols=5, spacing_m=150.0),
                              cell_size=100.0)


@pytest.fixture(scope="module")
def ubodt(arrays):
    return build_ubodt(arrays, delta=2000.0)


@pytest.fixture()
def fresh_slo():
    """Isolate the process-wide SLO/quality engines: tests that configure
    them must not leak an agreement objective into later suites."""
    yield
    obs_slo.configure(None)
    obs_quality._ENGINE = None


def _street_trace(arrays, n=10, uuid="veh-q", dt=5.0, row=2):
    nodes = [row * 5 + c for c in range(5)]
    t = np.linspace(0.05, 0.9, n)
    xs = np.interp(t, np.linspace(0, 1, 5), arrays.node_x[nodes])
    ys = np.interp(t, np.linspace(0, 1, 5), arrays.node_y[nodes])
    lat, lon = arrays.proj.to_latlon(xs, ys)
    return {
        "uuid": uuid,
        "trace": [{"lat": float(a), "lon": float(o), "time": 1000.0 + dt * i}
                  for i, (a, o) in enumerate(zip(lat, lon))],
        "match_options": {"mode": "auto", "report_levels": [0, 1],
                          "transition_levels": [0, 1]},
    }


# -- cohort bucketing --------------------------------------------------------


def test_gap_and_len_buckets():
    assert gap_bucket([0, 5, 10]) == "lt15"
    assert gap_bucket([0, 50, 100]) == "45-60"
    assert gap_bucket([0, 60]) == "ge60"
    assert gap_bucket([0, 20, 40]) == "15-30"
    assert gap_bucket([1000.0]) == "lt15"  # degenerate: one point
    assert len_bucket(8) == "short"
    assert len_bucket(64) == "med"
    assert len_bucket(500) == "long"


# -- kernel confidence aux ---------------------------------------------------


def test_quality_aux_off_by_default(arrays, ubodt):
    m = SegmentMatcher(arrays=arrays, ubodt=ubodt, config=MatcherConfig())
    out = m.match_many([_street_trace(arrays)])
    assert "_quality" not in out[0]


def test_quality_aux_attached_with_margins(arrays, ubodt):
    m = SegmentMatcher(arrays=arrays, ubodt=ubodt,
                       config=MatcherConfig(quality_aux=True))
    out = m.match_many([_street_trace(arrays, n=10)])
    q = out[0]["_quality"]
    assert q["n_points"] == 10
    assert len(q["edge"]) == 10
    assert q["breaks"] >= 1  # the window start counts
    assert q["margin_mean"] is not None and q["margin_mean"] >= 0
    assert q["margin_min"] is not None and q["margin_min"] >= 0
    assert 0.0 <= q["pool_exhausted_frac"] <= 1.0
    # the segments themselves are untouched by the aux programs
    ref = SegmentMatcher(arrays=arrays, ubodt=ubodt, config=MatcherConfig())
    assert out[0]["segments"] == ref.match_many(
        [_street_trace(arrays, n=10)])[0]["segments"]


def test_quality_aux_long_trace_folds_across_chunks(arrays, ubodt):
    cfg = MatcherConfig(quality_aux=True, length_buckets=[16, 32])
    m = SegmentMatcher(arrays=arrays, ubodt=ubodt, config=cfg)
    tr = _street_trace(arrays, n=80)  # 3 chunks at W=32
    q = m.match_many([tr])[0]["_quality"]
    assert q["n_points"] == 80 and len(q["edge"]) == 80
    assert q["margin_mean"] is not None


# -- per-request match_options parity ---------------------------------------


def test_match_options_override_equals_configured(arrays, ubodt):
    """A per-request sigma_z/beta/search_radius override must produce the
    EXACT wire output of a matcher configured with those values — the
    override is the same traced program with different scalars."""
    override = {"sigma_z": 6.5, "beta": 5.0, "search_radius": 40.0}
    m_default = SegmentMatcher(arrays=arrays, ubodt=ubodt,
                               config=MatcherConfig())
    m_tuned = SegmentMatcher(
        arrays=arrays, ubodt=ubodt,
        config=MatcherConfig(sigma_z=6.5, beta=5.0, search_radius=40.0))
    rng = np.random.default_rng(7)
    traces = []
    for i in range(4):
        t = _street_trace(arrays, n=12, uuid="veh-%d" % i, row=1 + i % 3)
        for p in t["trace"]:
            p["lat"] += float(rng.normal(0, 2e-5))
            p["lon"] += float(rng.normal(0, 2e-5))
        traces.append(t)
    tuned_req = [dict(t, match_options=dict(t["match_options"], **override))
                 for t in traces]
    out_override = m_default.match_many(tuned_req)
    out_tuned = m_tuned.match_many(traces)
    for a, b in zip(out_override, out_tuned):
        assert a == b


def test_match_options_mixed_batch_and_key(arrays, ubodt):
    m = SegmentMatcher(arrays=arrays, ubodt=ubodt, config=MatcherConfig())
    plain = _street_trace(arrays, uuid="plain")
    custom = _street_trace(arrays, uuid="custom")
    custom["match_options"]["beta"] = 9.0
    assert m._params_key(plain) == ()
    key = m._params_key(custom)
    assert key and key[1] == 9.0
    # override equal to the config default collapses to the fast path
    same = _street_trace(arrays, uuid="same")
    same["match_options"]["beta"] = m.cfg.beta
    assert m._params_key(same) == ()
    out = m.match_many([plain, custom, plain])
    assert all(r["segments"] for r in out)


def test_match_options_effective_clamps_radius(arrays, ubodt):
    m = SegmentMatcher(arrays=arrays, ubodt=ubodt, config=MatcherConfig())
    eff = m.effective_match_options({"search_radius": 10_000.0})
    assert eff["search_radius"] == float(arrays.cell_size) / 2.0
    # gps_accuracy is sigma-like and loses to an explicit sigma_z
    assert m.effective_match_options({"gps_accuracy": 9.0})["sigma_z"] == 9.0
    assert m.effective_match_options(
        {"gps_accuracy": 9.0, "sigma_z": 3.0})["sigma_z"] == 3.0
    # invalid values degrade to the config (the service 400s them first)
    assert (m.effective_match_options({"beta": "bogus"})["beta"]
            == m.cfg.beta)


# -- the shadow-oracle engine ------------------------------------------------


def test_engine_compare_scores_agreement(arrays, ubodt):
    m = SegmentMatcher(arrays=arrays, ubodt=ubodt,
                       config=MatcherConfig(quality_aux=True))
    fed = []
    eng = QualityEngine(m, sample_every=1, start_worker=False,
                        slo_feed=lambda v, w: fed.append((v, w)))
    tr = _street_trace(arrays, n=10)
    prod = m.match_many([tr])[0]["_quality"]["edge"]
    frac = eng.compare(tr, prod)
    assert frac == 1.0  # the device agrees with itself re-matched by brute
    assert fed and fed[-1] == (1.0, 10.0)
    rep = eng.report()
    assert rep["overall"]["agreement"] == 1.0
    assert rep["overall"]["points"] == 10
    (cohort,) = rep["cohorts"]
    assert cohort.startswith("gap=lt15|len=short|kernel=scan|")
    # a corrupted production answer scores below 1.0
    bad = list(prod)
    bad[0] = -1 if prod[0] >= 0 else 0
    frac2 = eng.compare(tr, bad)
    assert frac2 is not None and frac2 < 1.0


def test_engine_queue_bounded_and_drops(arrays, ubodt):
    m = SegmentMatcher(arrays=arrays, ubodt=ubodt,
                       config=MatcherConfig(quality_aux=True))
    eng = QualityEngine(m, sample_every=1, queue_max=2, start_worker=False,
                        slo_feed=lambda v, w: None)
    tr = _street_trace(arrays, n=4)
    q = {"edge": [0, 1, 2, 3]}
    takes = [eng.maybe_sample(tr, q) for _ in range(5)]
    assert takes == [True, True, False, False, False]
    assert eng._q.qsize() == 2
    assert eng.report()["samples_dropped"] == 3
    # no per-point edges -> skipped, never enqueued
    assert eng.maybe_sample(tr, {}) is False


def test_engine_sampling_cadence(arrays, ubodt):
    m = SegmentMatcher(arrays=arrays, ubodt=ubodt,
                       config=MatcherConfig(quality_aux=True))
    eng = QualityEngine(m, sample_every=4, queue_max=64, start_worker=False,
                        slo_feed=lambda v, w: None)
    tr = _street_trace(arrays, n=4)
    q = {"edge": [0, 1, 2, 3]}
    took = sum(eng.maybe_sample(tr, q) for _ in range(40))
    assert took == 10  # exactly 1-in-4


# -- the agreement SLO objective --------------------------------------------


def test_agreement_objective_math_and_alerting():
    clock = {"t": 1000.0}
    eng = SLOEngine([Objective("agreement", "agreement", 0.90)],
                    window_s=30.0, instrument=False,
                    clock=lambda: clock["t"])
    # healthy: mean 0.96 over the window -> ok, burn < 1
    for i in range(10):
        clock["t"] += 1.0
        eng.observe_sample("agreement", 0.96, weight=10.0)
    st = eng._objective_state(eng.objectives[0], clock["t"])
    assert st["ok"] and not st["alerting"]
    assert abs(st["value"] - 0.96) < 1e-6
    assert st["sample_weight"] == 100.0
    assert abs(eng.burn_rate(eng.objectives[0], 30.0) - 0.4) < 1e-6
    # drift: agreement collapses -> burn >> factor in BOTH pair windows
    # within one short window's worth of samples
    for i in range(6):
        clock["t"] += 1.0
        eng.observe_sample("agreement", 0.30, weight=50.0)
    st = eng._objective_state(eng.objectives[0], clock["t"])
    assert not st["ok"]
    assert st["alerting"], st
    # no samples at all: vacuously compliant, burns nothing
    eng2 = SLOEngine([Objective("agreement", "agreement", 0.90)],
                     window_s=30.0, instrument=False)
    st2 = eng2._objective_state(eng2.objectives[0], None)
    assert st2["ok"] and st2["value"] is None
    assert eng2.burn_rate(eng2.objectives[0], 30.0) == 0.0


def test_agreement_objective_spec_and_env(monkeypatch, fresh_slo):
    assert any(o.kind == "agreement"
               for o in obs_slo.objectives_from_spec({"agreement": 0.92}))
    monkeypatch.setenv("REPORTER_SLO_AGREEMENT", "0.88")
    objs = obs_slo.default_objectives()
    (agr,) = [o for o in objs if o.kind == "agreement"]
    assert agr.target == 0.88
    monkeypatch.delenv("REPORTER_SLO_AGREEMENT")
    assert not any(o.kind == "agreement"
                   for o in obs_slo.default_objectives())


# -- end to end through the service -----------------------------------------


def _mk_service(arrays, ubodt, quality=None, slo=None, **cfg_kw):
    from reporter_tpu.serve import ReporterService

    matcher = SegmentMatcher(arrays=arrays, ubodt=ubodt,
                             config=MatcherConfig(**cfg_kw))
    return ReporterService(matcher, max_wait_ms=2.0, quality=quality,
                           slo=slo)


def test_service_shadow_sampling_e2e(arrays, ubodt, fresh_slo):
    svc = _mk_service(arrays, ubodt, quality={"sample_every": 1},
                      slo={"window_s": 60, "availability": 0.95})
    assert svc.quality is not None
    assert svc.matcher._quality_aux  # configure() flipped it on
    for i in range(6):
        code, out = svc.handle_report(_street_trace(arrays, uuid="v%d" % i))
        assert code == 200
    assert svc.quality.drain(30)
    code, slo = svc.handle_slo({})
    assert code == 200
    q = slo["quality"]
    assert q["samples_compared"] == 6
    assert q["overall"]["agreement"] is not None
    assert q["overall"]["agreement"] >= 0.95  # clean street traces
    (agr,) = [o for o in slo["objectives"] if o["kind"] == "agreement"]
    assert agr["ok"] and not agr["alerting"]
    # the statusz quality line rides too
    _code, statusz = svc.handle_statusz()
    assert statusz["quality"]["agreement"] == q["overall"]["agreement"]


def test_service_debug_payload_and_low_margin_flight(arrays, ubodt,
                                                     monkeypatch, fresh_slo):
    # threshold high enough that every decode counts as low-margin
    monkeypatch.setenv("REPORTER_QUALITY_MARGIN_KEEP", "1e9")
    from reporter_tpu.obs import flight as obs_flight

    svc = _mk_service(arrays, ubodt, quality_aux=True)
    code, out = svc.handle_report(_street_trace(arrays, uuid="veh-dbg"),
                                  debug=True)
    assert code == 200
    dbg = out["debug"]
    assert dbg["quality"]["margin_mean"] is not None
    assert "edge" not in dbg["quality"]  # raw edges never reach the wire
    assert dbg["match_options"]["sigma_z"] == pytest.approx(4.07)
    # the wire payload carries no leaked matcher internals
    assert "_quality" not in out.get("segment_matcher", {})
    found = [e for e in obs_flight.RECORDER.snapshot(64)
             if e.get("retained") == "low_margin"]
    assert found, "low-margin trace must be flight-retained"


def test_service_rejects_bad_match_options(arrays, ubodt, fresh_slo):
    svc = _mk_service(arrays, ubodt)
    bad = _street_trace(arrays)
    bad["match_options"]["sigma_z"] = -2
    code, out = svc.handle_report(bad)
    assert code == 400 and "sigma_z" in out["error"]
    walk = _street_trace(arrays)
    walk["match_options"]["shape_match"] = "edge_walk"
    code, out = svc.handle_report(walk)
    assert code == 400 and "shape_match" in out["error"]
    snap = _street_trace(arrays)
    snap["match_options"]["shape_match"] = "map_snap"
    snap["match_options"]["gps_accuracy"] = 5.0
    code, _ = svc.handle_report(snap)
    assert code == 200


def test_quality_skew_trips_agreement_alert(arrays, ubodt, monkeypatch,
                                            fresh_slo):
    """The drift-injection contract (ISSUE acceptance): an armed
    quality_skew must trip the agreement burn alert within one window;
    the no-fault leg (test_service_shadow_sampling_e2e) must not."""
    monkeypatch.setenv("REPORTER_FAULT_QUALITY_SKEW", "60.0")
    faults.reset()
    try:
        svc = _mk_service(arrays, ubodt, quality={"sample_every": 1},
                          slo={"window_s": 30, "availability": 0.95})
        for i in range(10):
            code, _ = svc.handle_report(
                _street_trace(arrays, uuid="skew-%d" % i))
            assert code == 200  # the degradation is SILENT on the wire
        assert svc.quality.drain(30)
        code, slo = svc.handle_slo({})
        (agr,) = [o for o in slo["objectives"] if o["kind"] == "agreement"]
        assert agr["value"] is not None and agr["value"] < 0.9
        assert not agr["ok"]
        assert agr["alerting"], agr
        # the skewed snapshot also fails the quality gate (leg parity
        # with the CI rehearsal)
        assert slo["quality"]["overall"]["agreement"] < 0.9
    finally:
        monkeypatch.delenv("REPORTER_FAULT_QUALITY_SKEW")
        faults.reset()


def test_sampling_overhead_p99(arrays, ubodt, fresh_slo):
    """Shadow sampling must stay off the hot path: ≤5% p99 delta with
    sampling ON at a production cadence vs OFF, over the same request
    stream (plus a small absolute epsilon for scheduler jitter, the
    PR-1/2 overhead-bound pattern)."""
    n = 300
    traces = [_street_trace(arrays, uuid="ov-%d" % i, n=6)
              for i in range(n)]

    def p99(svc):
        lats = []
        for t in traces:
            t0 = time.perf_counter()
            code, _ = svc.handle_report(t)
            lats.append(time.perf_counter() - t0)
            assert code == 200
        lats.sort()
        return lats[int(0.99 * len(lats))]

    def run(sampling):
        svc = _mk_service(
            arrays, ubodt,
            quality={"sample_every": 8} if sampling else None,
            quality_aux=True)
        p99(svc)  # warm the dispatch path on both sides
        return min(p99(svc) for _ in range(3))

    t_off = run(False)
    t_on = run(True)
    # absolute epsilon sized for a single-CPU box running the full suite:
    # a p99 over 6-pt reports is ~15 ms, and one preempted slice adds tens
    # of ms of scheduler jitter that min-of-3 cannot fully absorb — the
    # systematic (per-request) overhead bound stays the 1.10x term
    assert t_on <= 1.10 * t_off + 0.050, (t_on, t_off)


# -- the quality gate --------------------------------------------------------


def _gate():
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "quality_gate",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools", "quality_gate.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _snap(overall_a, overall_n, cohorts=None):
    return {"overall": {"agreement": overall_a, "points": overall_n},
            "cohorts": cohorts or {}}


def test_quality_gate_verdicts(tmp_path):
    qg = _gate()

    def write(name, d):
        p = tmp_path / name
        p.write_text(json.dumps(d))
        return str(p)

    base = write("base.json", _snap(0.95, 5000, {
        "gap=45-60": {"agreement": 0.90, "points": 2000},
        "gap=lt15": {"agreement": 0.97, "points": 3000},
        "thin": {"agreement": 0.99, "points": 10},
    }))
    # same quality: OK
    rc, v = qg.gate(base, write("same.json", _snap(0.95, 5000, {
        "gap=45-60": {"agreement": 0.90, "points": 2000},
        "gap=lt15": {"agreement": 0.968, "points": 3000},
    })))
    assert rc == 0 and v["verdict"] == "OK"
    # a real regression in one cohort: rc 1
    rc, v = qg.gate(base, write("reg.json", _snap(0.95, 5000, {
        "gap=45-60": {"agreement": 0.70, "points": 2000},
        "gap=lt15": {"agreement": 0.97, "points": 3000},
    })))
    assert rc == 1
    bad = [r for r in v["rows"] if r["verdict"] == "REGRESSION"]
    assert bad and bad[0]["cohort"] == "gap=45-60"
    # thin cohorts are skipped, never judged
    rc, v = qg.gate(base, write("thin.json", _snap(0.95, 5000, {
        "thin": {"agreement": 0.0, "points": 5},
    })))
    assert rc == 0
    assert any(s["cohort"] == "thin" for s in v["skipped"])
    # tiny samples cannot fail on noise: 40 points at 0.85 vs base 0.95
    # sits inside 3 binomial sigmas
    rc, v = qg.gate(
        write("b2.json", _snap(0.95, 40)),
        write("f2.json", _snap(0.85, 40)))
    assert rc == 0, v
    # the absolute floor is baseline-independent
    rc, v = qg.gate(base, write("floor.json", _snap(0.94, 5000)),
                    min_agreement=0.97)
    assert rc == 1 and v["floor_violated"]
    # no samples: rc 2, an explicit INVALID
    rc, v = qg.gate(base, write("empty.json", _snap(None, 0)))
    assert rc == 2 and v["verdict"] == "INVALID"
    # a /debug/slo response is unwrapped automatically
    rc, _ = qg.gate(base, write("wrapped.json",
                                {"ok": True, "quality": _snap(0.95, 5000)}))
    assert rc == 0


# -- loadgen sparse-gap scenario --------------------------------------------


def test_loadgen_gap_sessions():
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "loadgen",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools", "loadgen.py"))
    lg = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lg)
    sessions = lg.synth_sessions(4, 12, window=6, grid=5, seed=3,
                                 gaps=[45.0, 60.0])
    assert len(sessions) == 4
    for i, (_uuid, reqs) in enumerate(sessions):
        ts = [p["time"] for p in reqs[0]["trace"]]
        gaps = np.diff(ts)
        want = 45.0 if i % 2 == 0 else 60.0
        assert np.allclose(gaps, want), (i, gaps[:3])
    # default stays the dense 5 s fleet
    dense = lg.synth_sessions(2, 12, window=6, grid=5, seed=3)
    ts = [p["time"] for p in dense[0][1][0]["trace"]]
    assert np.allclose(np.diff(ts), 5.0)


# -- fleet federation of the quality plane ----------------------------------


def test_federator_relays_agreement_to_fleet_engine():
    from reporter_tpu.obs import federation as obs_fed

    clock = {"t": 100.0}
    fleet = SLOEngine([], window_s=60.0, instrument=False,
                      clock=lambda: clock["t"])
    fed = obs_fed.Federator([], fleet_engine=fleet)
    statusz = {"replica": "rep-a",
               "slo": {"objectives": {"agreement": {"value": 0.93,
                                                    "target": 0.9}}}}
    fed._feed_fleet_quality(statusz)
    # the objective was added at the replica's target and the sample landed
    (agr,) = [o for o in fleet.objectives if o.kind == "agreement"]
    assert agr.target == 0.9
    st = fleet._objective_state(agr, clock["t"])
    assert st["value"] == pytest.approx(0.93)
    # a replica without quality data is a no-op, never an error
    fed._feed_fleet_quality({"replica": "rep-b", "slo": {"objectives": {}}})

    # fleet_quality aggregates the feeds' last statusz: mean/min + the
    # one-replica-diverging signal
    f1 = obs_fed.ReplicaFeed("http://a")
    f1.statusz = statusz
    f1.rid = "rep-a"
    f2 = obs_fed.ReplicaFeed("http://b")
    f2.statusz = {"replica": "rep-b",
                  "slo": {"objectives": {"agreement": {"value": 0.63,
                                                       "target": 0.9}}}}
    f2.rid = "rep-b"
    fed._feeds = [f1, f2]
    fq = fed.fleet_quality()
    assert fq["mean"] == pytest.approx(0.78)
    assert fq["min"] == pytest.approx(0.63)
    assert set(fq["replicas"]) == {"rep-a", "rep-b"}
