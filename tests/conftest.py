"""Test configuration.

Tests run on CPU with 8 virtual devices so multi-chip sharding logic is
exercised without TPU hardware (the driver separately dry-runs the multi-chip
path; bench.py runs on the real chip).  The env vars must be set before jax
is imported anywhere.
"""

import os

# hard override: the surrounding environment exports JAX_PLATFORMS=axon (the
# tunneled TPU); tests must run on the virtual-device CPU backend.  NB the
# env var alone is not enough -- sitecustomize imports jax before this file
# runs, so the config value is overridden again below after import.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# The environment's sitecustomize registers the 'axon' (tunneled TPU) PJRT
# plugin in every process, and jax's backend discovery initialises it even
# when JAX_PLATFORMS=cpu — hanging the whole test run if the tunnel is down.
# Tests only ever want the virtual-device CPU backend, so drop every other
# factory before the first backend lookup.
try:  # defensive: internal API
    from jax._src import xla_bridge

    for _name in list(getattr(xla_bridge, "_backend_factories", {})):
        if _name != "cpu":
            xla_bridge._backend_factories.pop(_name, None)
            # keep the platform name known: pallas-TPU interpret-mode tests
            # import lowering registrations that validate known_platforms()
            if _name not in xla_bridge._platform_aliases:
                xla_bridge._platform_aliases[_name] = _name
except Exception:  # pragma: no cover
    pass

# XLA compiles via the axon remote-compile path were the original reason for
# a persistent cache; it stays on because it also makes CPU reruns cheap.
jax.config.update("jax_compilation_cache_dir", os.path.join(os.path.dirname(__file__), "..", ".jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)
