#!/usr/bin/env bash
# Fleet chaos gating rehearsal (the CI `fleet-rehearsal` leg; runnable
# locally): tools/fleet.py boots 3 serve replicas behind the session-
# affine router (serve/router.py), tools/loadgen.py replays an open-loop
# synth fleet against the ROUTER, and mid-replay the fleet is abused the
# way production abuses it:
#
#   t+8s   one replica is SIGKILLed (no drain, no warning) — the
#          supervisor respawns it, the router fails its traffic over
#   t+16s  SIGUSR1 triggers a rolling restart (each replica gracefully
#          drained, respawned, waited healthy, one at a time)
#
# and the verdict must still hold:
#
#   1. loadgen's SLO verdict passes (rc 0): availability + p99 met over
#      the WHOLE run, kill and restarts included — and with --server-slo
#      the ROUTER's client-truth fleet verdict (GET /debug/slo) must
#      AGREE with loadgen's client-side one
#   2. zero non-shed client errors after the failover window: every
#      sample outside [kill, kill+2s) is 200/429/503 — a lost replica
#      may shed, it may NOT surface 5xx/resets/timeouts to clients
#   3. the affinity remap is confined: between the kill and the rolling
#      restart, the ONLY vehicles that changed replica are the ones the
#      dead replica owned (rendezvous hashing's promise, measured from
#      the X-Reporter-Replica echoes in the per-sample dump)
#
# plus the fleet observability plane (docs/observability.md "Fleet
# observability"):
#
#   4. federation consistency (chaos-free phase 0): the sum over
#      replicas of the federated replica-labeled reporter_requests_total
#      plus router sheds equals loadgen's client-observed request count,
#      and the per-replica split matches the --dump-samples distribution
#      exactly
#   5. the SIGKILLed replica's final snapshot stays visible on the
#      router's federated /metrics with a RISING staleness gauge while
#      the replica is down
#   6. at least one failover-masked request shows up as fleet-good /
#      replica-bad in the reporter_fleet_slo_masking_debt gauge
#   7. one stitched GET /debug/traces?id= for a failed-over request
#      returns ≥2 dispatch-attempt hop spans with the serving replica's
#      span tree spliced under them
#
# Usage: tests/fleet_rehearsal.sh [workdir]
set -euo pipefail

# shared spawn/trap/cleanup/wait helpers (tests/rehearsal_lib.sh)
. "$(dirname "$0")/rehearsal_lib.sh"
# snappy failover in the router's retry loop (the default backoff base is
# tuned for WAN egress, not a localhost rehearsal)
export REPORTER_RETRY_BASE_S="${REPORTER_RETRY_BASE_S:-0.05}"
# snappy federation so the SIGKILL staleness window is observable (the
# supervisor respawns a killed replica in under a second, so the stale
# bound must be tighter than the respawn)
export REPORTER_FEDERATION_PULL_S="${REPORTER_FEDERATION_PULL_S:-0.25}"
export REPORTER_FEDERATION_STALE_S="${REPORTER_FEDERATION_STALE_S:-0.75}"
# the router's client-truth fleet SLO states the SAME objectives loadgen
# asserts, so the --server-slo agreement check compares like with like
export REPORTER_SLO_AVAILABILITY=0.95
export REPORTER_SLO_P99_MS=8000
export REPORTER_SLO_P999_MS=0
export REPORTER_SLO_DEGRADED_FRAC=0
# ONE injected router->replica connect refusal: the first phase-0 request
# deterministically fails over, giving the stitched-trace assertion a
# failed-over trace whose winning replica is still alive (the chaos
# phase's own failovers race the rolling restart, which wipes replica
# flight recorders — a live-only assertion would be flaky)
export REPORTER_FAULT_ROUTER_CONNECT="refused:1"
# ...and ONE injected admission shed per replica: each replica 429s its
# first /report (burning ITS availability budget), the router rotates
# onward, the client sees 200 — the deterministic fleet-good/replica-bad
# requests the masking-debt assertion bills (a clean rolling restart can
# rotate traffic off so fast that no organic drain refusal ever occurs)
export REPORTER_FAULT_REPLICA_SHED="1"
# replicas 2..N replay replica 1's XLA compiles instead of redoing them
reh_init "${1:-}" reporter-fleet
export REPORTER_XLA_CACHE_DIR="$WORK/xla-cache"
ROUTER_PORT=18071
BASE_PORT=18072
echo "fleet rehearsal workdir: $WORK"

# ---- config (grid must match loadgen --grid; one length bucket keeps the
# --warmup grid small enough for CI) ----------------------------------------
cat > "$WORK/config.json" <<EOF
{
  "network": {"type": "grid", "rows": 8, "cols": 8, "spacing_m": 200},
  "matcher": {"sigma_z": 4.07, "beta": 3.0, "search_radius": 50.0,
              "length_buckets": [16],
              "warmup_batch_sizes": [1, 4, 16, 64]},
  "backend": "jax",
  "batch": {"max_batch": 64, "max_wait_ms": 5}
}
EOF

# ---- boot the fleet -------------------------------------------------------
python tools/fleet.py --config "$WORK/config.json" --replicas 3 \
    --base-port "$BASE_PORT" --router-port "$ROUTER_PORT" \
    --workdir "$WORK" --warmup --cpu-default --drain-grace 20 \
    > "$WORK/fleet.log" 2>&1 &
FLEET_PID=$!
reh_track_fleet "$FLEET_PID" "$WORK"

# deferred boot answers 200 while the engine is still attaching:
# readiness for the LOAD run is an attached backend, else the replay's
# head just measures "service initialising" 503s
if ! reh_wait_fleet "http://127.0.0.1:$ROUTER_PORT" 3 "$BASE_PORT" 3 600; then
    echo "FAIL: fleet never reached 3 available replicas; fleet log tail:"
    tail -30 "$WORK/fleet.log"
    for f in "$WORK"/replica-*.log "$WORK"/router.log; do
        echo "--- $f"; tail -10 "$f" 2>/dev/null || true
    done
    exit 1
fi
echo "fleet up: 3 replicas behind the router"

# ---- phase 0: federation consistency, chaos-free --------------------------
# a short clean replay, then the invariant: every client-observed request
# is accounted for EXACTLY ONCE across the federated replica-labeled
# counters (+ router sheds), and the per-replica split matches the
# X-Reporter-Replica echoes in the sample dump
python tools/loadgen.py --url "http://127.0.0.1:$ROUTER_PORT" \
    --rate 10 --duration 6 --vehicles 24 --points 48 --window 16 --grid 8 \
    --seed 7 --concurrency 16 --timeout-s 8 \
    --slo-availability 0.95 --slo-p99-ms 8000 \
    --dump-samples "$WORK/phase0_samples.jsonl" \
    --out "$WORK/loadgen_phase0.json"
python - "$WORK" "http://127.0.0.1:$ROUTER_PORT" <<'EOF'
import json, sys, urllib.request

work, router = sys.argv[1], sys.argv[2]
sys.path.insert(0, ".")
from reporter_tpu.obs.quantile import parse_metrics

rows = [json.loads(l) for l in open(work + "/phase0_samples.jsonl")]
observed = {}
for r in rows:
    if r["replica"] and r["code"] == 200:
        observed[r["replica"]] = observed.get(r["replica"], 0) + 1
n200 = sum(1 for r in rows if r["code"] == 200)
with urllib.request.urlopen(router + "/metrics?pull=1", timeout=10) as f:
    m = parse_metrics(f.read().decode())
federated = {}
resheds = 0
for lv, v in m.get("reporter_requests_total", {}).items():
    d = dict(lv)
    if "replica" not in d or d.get("endpoint") != "report":
        continue
    if d.get("outcome") == "shed":
        # a replica-side shed is RE-DISPATCHED by the router: the client
        # observes one request, the fleet counts the shed AND the
        # winner's ok — so sheds are accounted separately, not summed
        # into the per-request ledger (the injected REPLICA_SHED=1 per
        # replica makes this leg exercise the distinction)
        resheds += int(v)
        continue
    federated[d["replica"]] = federated.get(d["replica"], 0) + int(v)
shed = int(m.get("reporter_router_shed_total", {}).get((), 0))
# the invariant, exact on successes: every client-observed 200 is
# counted by EXACTLY ONE replica on the federated scrape — nothing
# double-counted across failovers, nothing lost
assert sum(federated.values()) == n200, (
    "federation consistency broken: %d federated non-shed counts != "
    "%d client-observed 200s (%r)" % (sum(federated.values()), n200,
                                      federated))
assert federated == observed, (
    "per-replica split mismatch: federated %r vs client-observed %r"
    % (federated, observed))
# ...and exhaustive on the rest: every non-200 client row is a shed of
# some kind, all visible on the same scrape (the router's own gate or a
# replica-side shed leg) — the ledger balances
assert len(rows) - n200 <= shed + resheds, (
    "%d client non-200s but only %d router + %d replica sheds visible"
    % (len(rows) - n200, shed, resheds))
assert resheds >= 3, (
    "the injected per-replica admission sheds never fired (%d)" % resheds)
print("phase 0 consistency OK: %d requests (%d ok), split %s, %d router "
      "sheds, %d replica sheds re-dispatched"
      % (len(rows), n200, dict(sorted(federated.items())), shed, resheds))

# 7. the stitched trace: the injected connect refusal made the first
# phase-0 request fail over; its router span must carry >= 2
# dispatch-attempt hops with the serving replica's span tree spliced
# under them (the winning leg's X-Reporter-Flight-Keep pinned it)
def get(url):
    with urllib.request.urlopen(url, timeout=10) as f:
        return json.loads(f.read().decode())

traces = get(router + "/debug/traces?n=200")["traces"]
candidates = [t for t in traces if t.get("attempts", 1) >= 2
              and t.get("status") == "ok"]
assert candidates, ("the injected connect refusal produced no retained "
                    "failed-over router span")
stitched = None
for t in candidates:
    out = get(router + "/debug/traces?id=%s" % t["trace_id"])
    s = out["stitched"]
    hops = [h for h in s.get("hops", []) if h.get("span") == "dispatch"]
    if len(hops) >= 2 and s.get("children"):
        stitched = out
        break
assert stitched is not None, (
    "no stitched router+replica span tree among %d failed-over traces"
    % len(candidates))
s = stitched["stitched"]
# the losing hop is visible: a transport error or a shed/5xx code
assert any(h.get("outcome") != "200" for h in s["hops"]
           if h.get("span") == "dispatch"), s["hops"]
assert any(e.get("endpoint") == "report" for e in s["children"])
assert all(e.get("trace_id") == stitched["trace_id"]
           for e in s["children"])
print("stitched trace %s: %d dispatch hops, %d replica spans spliced"
      % (stitched["trace_id"],
         len([h for h in s["hops"] if h.get("span") == "dispatch"]),
         len(s["children"])))
EOF

# ---- the fleet-plane watcher: samples the router's federated surfaces
# through the chaos window (staleness + masking debt are TRANSIENT — the
# respawn refreshes the snapshot, so they must be observed live) --------
python - "$WORK" "http://127.0.0.1:$ROUTER_PORT" <<'EOF' &
import json, os, re, sys, time, urllib.request

work, router = sys.argv[1], sys.argv[2]
obs = {"stale_seen": False, "stale_age_max": 0.0,
       "stale_snapshot_present": False, "masking_debt_max": 0.0}
path = work + "/plane_watch.json"
stale_re = re.compile(
    r'reporter_federation_snapshot_stale\{replica="rep-1"\} 1\b')
age_re = re.compile(
    r'reporter_federation_snapshot_age_seconds\{replica="rep-1"\} ([\d.]+)')
debt_re = re.compile(
    r'reporter_fleet_slo_masking_debt\{objective="[^"]+"\} ([\d.eE+-]+)')
while True:
    try:
        with urllib.request.urlopen(router + "/metrics", timeout=3) as f:
            text = f.read().decode()
        age = age_re.search(text)
        if stale_re.search(text) and age:
            obs["stale_seen"] = True
            obs["stale_age_max"] = max(obs["stale_age_max"],
                                       float(age.group(1)))
            # the dead replica's LAST snapshot must still be rendered
            if re.search(r'reporter_requests_total\{replica="rep-1"',
                         text):
                obs["stale_snapshot_present"] = True
        for m in debt_re.finditer(text):
            obs["masking_debt_max"] = max(obs["masking_debt_max"],
                                          float(m.group(1)))
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(obs, f)
        os.replace(tmp, path)
    except Exception:
        pass  # router mid-churn: keep sampling
    time.sleep(0.05)
EOF
WATCHER_PID=$!
reh_track_watcher "$WATCHER_PID"

# ---- open-loop replay against the ROUTER, chaos mid-load ------------------
python tools/loadgen.py --url "http://127.0.0.1:$ROUTER_PORT" \
    --rate 15 --duration 30 --vehicles 24 --points 48 --window 16 --grid 8 \
    --seed 11 --concurrency 32 --timeout-s 8 \
    --slo-availability 0.95 --slo-p99-ms 8000 --server-slo \
    --dump-samples "$WORK/samples.jsonl" \
    --out "$WORK/loadgen_fleet.json" &
LOADGEN_PID=$!

sleep 8
VICTIM_PID=$(python -c "
import json; s = json.load(open('$WORK/fleet.json'))
print(s['replicas'][1]['pid'])")
KILL_EPOCH=$(python -c "import time; print(time.time())")
# freeze-then-kill: the SIGSTOP holds the replica wedged (not yet dead)
# for 2 s, so the federation's staleness window is wide enough to
# observe deterministically — the supervisor respawns a SIGKILLed
# replica in under a second, faster than any sane stale bound.  The
# router sees exactly what a wedged process looks like: probes time
# out, live legs hang until the kill resets them, pulls go stale.
kill -STOP "$VICTIM_PID"
echo "SIGSTOPped replica rep-1 (pid $VICTIM_PID) at $KILL_EPOCH"
sleep 2
kill -9 "$VICTIM_PID"
echo "SIGKILLed replica rep-1 (pid $VICTIM_PID)"

sleep 6
RESTART_EPOCH=$(python -c "import time; print(time.time())")
kill -USR1 "$FLEET_PID"
echo "rolling restart requested at $RESTART_EPOCH"

set +e
wait "$LOADGEN_PID"
LOADGEN_RC=$?
set -e
if [ "$LOADGEN_RC" != 0 ]; then
    echo "FAIL: loadgen rc $LOADGEN_RC — the fleet violated its SLO under"
    echo "      a SIGKILL + rolling restart (artifact: loadgen_fleet.json)"
    python -c "
import json; a = json.load(open('$WORK/loadgen_fleet.json'))
print(json.dumps({k: a[k] for k in ('status', 'quantiles', 'slo')}, indent=1))" \
        2>/dev/null || true
    exit 1
fi
echo "loadgen SLO verdict: PASS (rc 0) under kill + rolling restart"
echo "  (incl. --server-slo: the router's client-truth fleet verdict agrees)"

# ---- fleet plane: staleness, masking debt, stitched failover trace --------
reh_untrack_watchers
python - "$WORK" "http://127.0.0.1:$ROUTER_PORT" <<'EOF'
import json, sys, urllib.request

work, router = sys.argv[1], sys.argv[2]

# 5. the SIGKILLed replica's final snapshot stayed visible with a rising
# staleness gauge (observed LIVE by the watcher: the respawn refreshes
# the snapshot, so the window is transient by design)
w = json.load(open(work + "/plane_watch.json"))
assert w["stale_seen"], (
    "the dead replica never showed a stale federated snapshot: %r" % w)
assert w["stale_snapshot_present"], (
    "the dead replica's last snapshot vanished from the federated "
    "render while stale: %r" % w)
assert w["stale_age_max"] > 0, w

# 6. at least one failover-masked request: replica-level burn the fleet
# verdict never saw, billed by the masking-debt gauge
assert w["masking_debt_max"] > 0, (
    "no masking debt observed across a SIGKILL + rolling restart — "
    "failover-masked replica burn is not being billed: %r" % w)
print("staleness observed (age max %.1fs, snapshot retained); "
      "masking debt max %.3f" % (w["stale_age_max"], w["masking_debt_max"]))
EOF

# the supervisor's own federation artifact exists and carries the herd
python - "$WORK" <<'EOF'
import json, sys

fed = json.load(open(sys.argv[1] + "/federation.json"))
assert set(fed["replicas"]) >= {"rep-0", "rep-2"}, fed["replicas"].keys()
assert fed["merged"], "supervisor federation dump carries no merged snapshot"
print("supervisor federation.json OK: %d replicas, %d merged families"
      % (len(fed["replicas"]), len(fed["merged"])))
EOF

# ---- failover-window errors + affinity confinement ------------------------
python - "$WORK" "$KILL_EPOCH" "$RESTART_EPOCH" <<'EOF'
import json, sys

work, kill_epoch, restart_epoch = sys.argv[1], float(sys.argv[2]), float(sys.argv[3])
FAILOVER_WINDOW_S = 2.0
rows = [json.loads(l) for l in open(work + "/samples.jsonl")]
assert rows, "empty sample dump"

# 1. zero non-shed client errors outside the failover window: a request
# is allowed to be shed (429) or to see a drain/unavailable 503 (the
# router retries those; a residue is shed-class), NEVER a 5xx/timeout
allowed = {200, 429, 503}
bad = [r for r in rows if r["code"] not in allowed
       and not (kill_epoch <= r["sched_epoch"] < kill_epoch + FAILOVER_WINDOW_S)]
assert not bad, (
    "non-shed client errors outside the failover window: %r" % bad[:5])

# 2. affinity remap confined to the SIGKILLed replica's vehicles,
# measured between the kill (+failover window) and the rolling restart:
# a vehicle "moved" if ANY of its phase-2 responses came from a replica
# other than its pre-kill primary (the supervisor respawns the victim
# fast, so a last-assignment view would under-measure the remap)
phase1 = {}
for r in sorted((r for r in rows if r["done_epoch"] < kill_epoch),
                key=lambda r: r["done_epoch"]):
    if r["replica"] and r["code"] == 200:
        phase1[r["uuid"]] = r["replica"]
phase2_rows = [r for r in rows
               if kill_epoch + FAILOVER_WINDOW_S <= r["sched_epoch"]
               and r["done_epoch"] < restart_epoch
               and r["replica"] and r["code"] == 200]
assert phase2_rows, "no samples between kill and rolling restart"
dead = "rep-1"
dead_vehicles = {u for u, rid in phase1.items() if rid == dead}
assert dead_vehicles, "the killed replica owned no vehicles pre-kill?"
moved = {r["uuid"] for r in phase2_rows
         if r["uuid"] in phase1 and r["replica"] != phase1[r["uuid"]]}
stray = moved - dead_vehicles
assert not stray, (
    "vehicles moved that the dead replica never owned: %r "
    "(affinity remap not confined)" % sorted(stray)[:10])
assert moved, ("the dead replica's vehicles never landed elsewhere "
               "during its downtime — remap not measured")

dist = {}
for r in rows:
    if r["replica"]:
        dist[r["replica"]] = dist.get(r["replica"], 0) + 1
print("failover window clean; %d/%d of the dead replica's vehicles "
      "remapped, 0 stray moves; per-replica distribution: %s"
      % (len(moved), len(dead_vehicles), dict(sorted(dist.items()))))
EOF

# ---- graceful fleet drain: exit 0, nothing stranded -----------------------
reh_stop_fleet
echo "fleet rehearsal OK (artifacts in $WORK)"
