#!/usr/bin/env bash
# Fleet chaos gating rehearsal (the CI `fleet-rehearsal` leg; runnable
# locally): tools/fleet.py boots 3 serve replicas behind the session-
# affine router (serve/router.py), tools/loadgen.py replays an open-loop
# synth fleet against the ROUTER, and mid-replay the fleet is abused the
# way production abuses it:
#
#   t+8s   one replica is SIGKILLed (no drain, no warning) — the
#          supervisor respawns it, the router fails its traffic over
#   t+16s  SIGUSR1 triggers a rolling restart (each replica gracefully
#          drained, respawned, waited healthy, one at a time)
#
# and the verdict must still hold:
#
#   1. loadgen's SLO verdict passes (rc 0): availability + p99 met over
#      the WHOLE run, kill and restarts included
#   2. zero non-shed client errors after the failover window: every
#      sample outside [kill, kill+2s) is 200/429/503 — a lost replica
#      may shed, it may NOT surface 5xx/resets/timeouts to clients
#   3. the affinity remap is confined: between the kill and the rolling
#      restart, the ONLY vehicles that changed replica are the ones the
#      dead replica owned (rendezvous hashing's promise, measured from
#      the X-Reporter-Replica echoes in the per-sample dump)
#
# Usage: tests/fleet_rehearsal.sh [workdir]
set -euo pipefail

cd "$(dirname "$0")/.."
export PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}"
export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
# snappy failover in the router's retry loop (the default backoff base is
# tuned for WAN egress, not a localhost rehearsal)
export REPORTER_RETRY_BASE_S="${REPORTER_RETRY_BASE_S:-0.05}"
# replicas 2..N replay replica 1's XLA compiles instead of redoing them
WORK="${1:-$(mktemp -d /tmp/reporter-fleet.XXXXXX)}"
mkdir -p "$WORK"
export REPORTER_XLA_CACHE_DIR="$WORK/xla-cache"
ROUTER_PORT=18071
BASE_PORT=18072
echo "fleet rehearsal workdir: $WORK"

# ---- trap-based cleanup: NO exit path may strand a listener ---------------
FLEET_PID=""
cleanup() {
    if [ -n "$FLEET_PID" ] && kill -0 "$FLEET_PID" 2>/dev/null; then
        kill "$FLEET_PID" 2>/dev/null || true
        for _ in $(seq 1 40); do
            kill -0 "$FLEET_PID" 2>/dev/null || break
            sleep 0.5
        done
        kill -9 "$FLEET_PID" 2>/dev/null || true
    fi
    # belt-and-braces: any replica/router pid still in the state file
    if [ -f "$WORK/fleet.json" ]; then
        python - "$WORK/fleet.json" <<'EOF' 2>/dev/null || true
import json, os, signal, sys
state = json.load(open(sys.argv[1]))
pids = [state.get("router", {}).get("pid")] + [
    r.get("pid") for r in state.get("replicas", [])]
for pid in pids:
    if pid:
        try:
            os.kill(pid, signal.SIGKILL)
        except OSError:
            pass
EOF
    fi
}
trap cleanup EXIT

# ---- config (grid must match loadgen --grid; one length bucket keeps the
# --warmup grid small enough for CI) ----------------------------------------
cat > "$WORK/config.json" <<EOF
{
  "network": {"type": "grid", "rows": 8, "cols": 8, "spacing_m": 200},
  "matcher": {"sigma_z": 4.07, "beta": 3.0, "search_radius": 50.0,
              "length_buckets": [16],
              "warmup_batch_sizes": [1, 4, 16, 64]},
  "backend": "jax",
  "batch": {"max_batch": 64, "max_wait_ms": 5}
}
EOF

# ---- boot the fleet -------------------------------------------------------
python tools/fleet.py --config "$WORK/config.json" --replicas 3 \
    --base-port "$BASE_PORT" --router-port "$ROUTER_PORT" \
    --workdir "$WORK" --warmup --cpu-default --drain-grace 20 \
    > "$WORK/fleet.log" 2>&1 &
FLEET_PID=$!

if ! python - <<EOF
import json, sys, time, urllib.request

def up(url, need_backend):
    try:
        h = json.load(urllib.request.urlopen(url + "/health", timeout=2))
    except Exception:
        return False
    if need_backend:
        # deferred boot answers 200 while the engine is still attaching:
        # readiness for the LOAD run is an attached backend, else the
        # replay's head just measures "service initialising" 503s
        return h.get("status") == "ok" and bool(h.get("backend"))
    return h.get("available") == 3

deadline = time.monotonic() + 600
replicas = ["http://127.0.0.1:%d" % ($BASE_PORT + i) for i in range(3)]
while time.monotonic() < deadline:
    if (all(up(u, True) for u in replicas)
            and up("http://127.0.0.1:$ROUTER_PORT", False)):
        sys.exit(0)
    time.sleep(1)
sys.exit(1)
EOF
then
    echo "FAIL: fleet never reached 3 available replicas; fleet log tail:"
    tail -30 "$WORK/fleet.log"
    for f in "$WORK"/replica-*.log "$WORK"/router.log; do
        echo "--- $f"; tail -10 "$f" 2>/dev/null || true
    done
    exit 1
fi
echo "fleet up: 3 replicas behind the router"

# ---- open-loop replay against the ROUTER, chaos mid-load ------------------
python tools/loadgen.py --url "http://127.0.0.1:$ROUTER_PORT" \
    --rate 15 --duration 30 --vehicles 24 --points 48 --window 16 --grid 8 \
    --seed 11 --concurrency 32 --timeout-s 8 \
    --slo-availability 0.95 --slo-p99-ms 8000 \
    --dump-samples "$WORK/samples.jsonl" \
    --out "$WORK/loadgen_fleet.json" &
LOADGEN_PID=$!

sleep 8
VICTIM_PID=$(python -c "
import json; s = json.load(open('$WORK/fleet.json'))
print(s['replicas'][1]['pid'])")
KILL_EPOCH=$(python -c "import time; print(time.time())")
kill -9 "$VICTIM_PID"
echo "SIGKILLed replica rep-1 (pid $VICTIM_PID) at $KILL_EPOCH"

sleep 8
RESTART_EPOCH=$(python -c "import time; print(time.time())")
kill -USR1 "$FLEET_PID"
echo "rolling restart requested at $RESTART_EPOCH"

set +e
wait "$LOADGEN_PID"
LOADGEN_RC=$?
set -e
if [ "$LOADGEN_RC" != 0 ]; then
    echo "FAIL: loadgen rc $LOADGEN_RC — the fleet violated its SLO under"
    echo "      a SIGKILL + rolling restart (artifact: loadgen_fleet.json)"
    python -c "
import json; a = json.load(open('$WORK/loadgen_fleet.json'))
print(json.dumps({k: a[k] for k in ('status', 'quantiles', 'slo')}, indent=1))" \
        2>/dev/null || true
    exit 1
fi
echo "loadgen SLO verdict: PASS (rc 0) under kill + rolling restart"

# ---- failover-window errors + affinity confinement ------------------------
python - "$WORK" "$KILL_EPOCH" "$RESTART_EPOCH" <<'EOF'
import json, sys

work, kill_epoch, restart_epoch = sys.argv[1], float(sys.argv[2]), float(sys.argv[3])
FAILOVER_WINDOW_S = 2.0
rows = [json.loads(l) for l in open(work + "/samples.jsonl")]
assert rows, "empty sample dump"

# 1. zero non-shed client errors outside the failover window: a request
# is allowed to be shed (429) or to see a drain/unavailable 503 (the
# router retries those; a residue is shed-class), NEVER a 5xx/timeout
allowed = {200, 429, 503}
bad = [r for r in rows if r["code"] not in allowed
       and not (kill_epoch <= r["sched_epoch"] < kill_epoch + FAILOVER_WINDOW_S)]
assert not bad, (
    "non-shed client errors outside the failover window: %r" % bad[:5])

# 2. affinity remap confined to the SIGKILLed replica's vehicles,
# measured between the kill (+failover window) and the rolling restart:
# a vehicle "moved" if ANY of its phase-2 responses came from a replica
# other than its pre-kill primary (the supervisor respawns the victim
# fast, so a last-assignment view would under-measure the remap)
phase1 = {}
for r in sorted((r for r in rows if r["done_epoch"] < kill_epoch),
                key=lambda r: r["done_epoch"]):
    if r["replica"] and r["code"] == 200:
        phase1[r["uuid"]] = r["replica"]
phase2_rows = [r for r in rows
               if kill_epoch + FAILOVER_WINDOW_S <= r["sched_epoch"]
               and r["done_epoch"] < restart_epoch
               and r["replica"] and r["code"] == 200]
assert phase2_rows, "no samples between kill and rolling restart"
dead = "rep-1"
dead_vehicles = {u for u, rid in phase1.items() if rid == dead}
assert dead_vehicles, "the killed replica owned no vehicles pre-kill?"
moved = {r["uuid"] for r in phase2_rows
         if r["uuid"] in phase1 and r["replica"] != phase1[r["uuid"]]}
stray = moved - dead_vehicles
assert not stray, (
    "vehicles moved that the dead replica never owned: %r "
    "(affinity remap not confined)" % sorted(stray)[:10])
assert moved, ("the dead replica's vehicles never landed elsewhere "
               "during its downtime — remap not measured")

dist = {}
for r in rows:
    if r["replica"]:
        dist[r["replica"]] = dist.get(r["replica"], 0) + 1
print("failover window clean; %d/%d of the dead replica's vehicles "
      "remapped, 0 stray moves; per-replica distribution: %s"
      % (len(moved), len(dead_vehicles), dict(sorted(dist.items()))))
EOF

# ---- graceful fleet drain: exit 0, nothing stranded -----------------------
kill "$FLEET_PID"
set +e
wait "$FLEET_PID"
FLEET_RC=$?
set -e
FLEET_PID=""
if [ "$FLEET_RC" != 0 ]; then
    echo "FAIL: fleet supervisor exited rc $FLEET_RC on drain; log tail:"
    tail -30 "$WORK/fleet.log"
    exit 1
fi
echo "fleet rehearsal OK (artifacts in $WORK)"
