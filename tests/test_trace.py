"""End-to-end trace propagation, the flight recorder's tail-sampling
invariants, histogram exemplars, structured logging, and the always-on
tracing overhead bound."""

import io
import json
import logging
import threading
import time
import urllib.request

import numpy as np
import pytest

from reporter_tpu.obs import flight as obs_flight
from reporter_tpu.obs import log as obs_log
from reporter_tpu.obs import trace as obs_trace
from reporter_tpu.obs.flight import FlightRecorder
from reporter_tpu.obs.metrics import Registry, merge
from reporter_tpu.obs.trace import Span


# -- trace context ----------------------------------------------------------


def test_trace_id_accept_and_generate():
    assert obs_trace.accept_trace_id("abc-123.X_z") == "abc-123.X_z"
    assert obs_trace.accept_trace_id("  padded  ") == "padded"
    assert obs_trace.accept_trace_id(None) is None
    assert obs_trace.accept_trace_id("") is None
    assert obs_trace.accept_trace_id("bad id with spaces") is None
    assert obs_trace.accept_trace_id("x" * 65) is None  # too long
    assert obs_trace.accept_trace_id('evil"header\r\n') is None
    generated = obs_trace.new_trace_id()
    assert obs_trace.accept_trace_id(generated) == generated


def test_span_context_binding():
    assert obs_trace.current_span() is None
    assert obs_trace.current_trace_id() is None
    span = Span("outer", trace_id="tid-outer")
    with obs_trace.bind(span):
        assert obs_trace.current_span() is span
        assert obs_trace.current_trace_id() == "tid-outer"
        with obs_trace.bind(Span("inner")):
            assert obs_trace.current_span().name == "inner"
        assert obs_trace.current_span() is span
        # bind(None) is a no-op, not a reset
        with obs_trace.bind(None):
            assert obs_trace.current_span() is span
    assert obs_trace.current_span() is None


def test_context_is_per_thread():
    seen = {}

    def worker():
        seen["in_thread"] = obs_trace.current_trace_id()

    with obs_trace.bind(Span("main", trace_id="main-tid")):
        t = threading.Thread(target=worker)
        t.start()
        t.join()
    assert seen["in_thread"] is None  # fresh thread, fresh context


def test_span_fail_and_breakdown():
    span = Span("report", trace_id="t1")
    span.mark("queue_wait_s", 0.001)
    span.fail(ValueError("boom"))
    span.finish()
    out = span.breakdown()
    assert out["trace_id"] == "t1" and len(out["span_id"]) == 16
    assert out["timings"]["total_s"] >= 0
    assert span.status == "error" and "boom" in span.error


# -- flight recorder tail sampling -----------------------------------------


def _mk_span(status="ok", total_s=0.001, name="report"):
    span = Span(name)
    if status != "ok":
        span.fail("synthetic", status=status)
    span.timings["total_s"] = total_s
    return span


def test_tail_sampling_errors_and_slow_always_retained():
    rec = FlightRecorder(capacity=16, slow_ms=100.0, sample_every=5)
    err = _mk_span(status="error")
    slow = _mk_span(total_s=0.5)
    assert rec.record(err) == "error"
    assert rec.record(slow) == "slow"
    # flood with healthy fast traffic: the error/slow entries must survive
    for _ in range(500):
        rec.record(_mk_span())
    ids = {t["trace_id"] for t in rec.snapshot(64)}
    assert err.trace_id in ids and slow.trace_id in ids


def test_tail_sampling_one_in_n_and_bounded():
    rec = FlightRecorder(capacity=8, slow_ms=10_000.0, sample_every=10)
    decisions = [rec.record(_mk_span()) for _ in range(100)]
    assert decisions.count("sampled") == 10
    assert decisions.count("dropped") == 90
    # ring bounded under load regardless of volume
    for _ in range(1000):
        rec.record(_mk_span())
        rec.record(_mk_span(status="error"))
    s = rec.summary()
    assert s["retained_errors_slow"] <= 8 and s["retained_sampled"] <= 8
    assert len(rec.snapshot(1000)) <= 16


def test_snapshot_prefers_kept_traces_on_cut():
    rec = FlightRecorder(capacity=8, slow_ms=100.0, sample_every=1)
    errs = [_mk_span(status="error") for _ in range(4)]
    for e in errs:
        rec.record(e)
    for _ in range(8):
        rec.record(_mk_span())  # sample_every=1: all retained as sampled
    cut = rec.snapshot(4)
    assert len(cut) == 4
    assert {t["trace_id"] for t in cut} == {e.trace_id for e in errs}


def test_flight_dump_roundtrip(tmp_path):
    rec = FlightRecorder(capacity=4, slow_ms=100.0, sample_every=1)
    span = _mk_span(status="error")
    rec.record(span)
    path = str(tmp_path / "flight.json")
    assert rec.dump(path) == path
    data = json.loads(open(path).read())
    assert data["summary"]["capacity"] == 4
    assert data["traces"][0]["trace_id"] == span.trace_id
    # empty recorder: no file written
    assert FlightRecorder(capacity=4).dump(str(tmp_path / "empty.json")) is None


def test_shutdown_hook_runs_dump(monkeypatch, tmp_path):
    from reporter_tpu.utils import shutdown

    calls = []
    monkeypatch.setattr(shutdown, "_HOOKS", [])
    shutdown.on_shutdown(lambda: calls.append(1))
    shutdown.on_shutdown(lambda: (_ for _ in ()).throw(RuntimeError("x")))
    shutdown.run_shutdown_hooks()  # hook failures are swallowed
    assert calls == [1]


# -- histogram exemplars ----------------------------------------------------


def test_histogram_exemplars_in_snapshot_not_render():
    reg = Registry()
    h = reg.histogram("t_lat_seconds", "latency", buckets=(0.01, 0.1, 1.0))
    h.observe(0.005)                        # no exemplar
    h.observe(0.05, exemplar="trace-a")
    h.observe(0.07, exemplar="trace-b")     # slower: replaces trace-a's bucket
    h.observe(0.5, exemplar="trace-c")
    s = reg.snapshot()["t_lat_seconds"]["samples"][0][1]
    assert s["exemplars"] == [[1, 0.07, "trace-b"], [2, 0.5, "trace-c"]]
    # 0.0.4 text exposition carries no exemplar syntax
    assert "trace-" not in reg.render()


def test_histogram_exemplars_merge_keeps_slowest():
    rega, regb = Registry(), Registry()
    for reg, v, tid in ((rega, 0.03, "a"), (regb, 0.09, "b")):
        reg.histogram("t_lat", buckets=(0.01, 0.1)).observe(v, exemplar=tid)
    merged = merge(rega.snapshot(), regb.snapshot())
    assert merged["t_lat"]["samples"][0][1]["exemplars"] == [[1, 0.09, "b"]]
    # a snapshot without exemplars merges cleanly with one that has them
    regc = Registry()
    regc.histogram("t_lat", buckets=(0.01, 0.1)).observe(0.02)
    merged = merge(regc.snapshot(), rega.snapshot())
    assert merged["t_lat"]["samples"][0][1]["count"] == 2
    assert merged["t_lat"]["samples"][0][1]["exemplars"] == [[1, 0.03, "a"]]


# -- structured logging -----------------------------------------------------


def _capture_logger(fmt):
    stream = io.StringIO()
    handler = logging.StreamHandler(stream)
    handler.setFormatter(obs_log.JsonFormatter() if fmt == "json"
                         else obs_log.TextFormatter(obs_log.TEXT_FORMAT))
    logger = logging.getLogger("test_trace.%s.%d" % (fmt, id(stream)))
    logger.handlers[:] = [handler]
    logger.setLevel(logging.DEBUG)
    logger.propagate = False
    return logger, stream


def test_json_log_attaches_current_trace_id():
    logger, stream = _capture_logger("json")
    with obs_trace.bind(Span("report", trace_id="tid-json")):
        logger.info("inside %d", 42)
    logger.info("outside")
    lines = [json.loads(l) for l in stream.getvalue().strip().splitlines()]
    assert lines[0]["msg"] == "inside 42"
    assert lines[0]["trace_id"] == "tid-json"
    assert lines[0]["level"] == "INFO"
    assert "trace_id" not in lines[1]


def test_event_fields_json_and_text():
    logger, stream = _capture_logger("json")
    obs_log.event(logger, "relay_probe", open=False, ports=[], skipme=None)
    line = json.loads(stream.getvalue().strip())
    assert line["event"] == "relay_probe"
    assert line["open"] is False and line["ports"] == []
    assert "skipme" not in line  # None fields dropped

    logger, stream = _capture_logger("text")
    with obs_trace.bind(Span("s", trace_id="tid-text")):
        obs_log.event(logger, "compile_stall", shape="64x64", seconds=1.5)
    text = stream.getvalue().strip()
    assert "compile_stall" in text
    assert "shape=64x64" in text and "seconds=1.5" in text
    assert "trace_id=tid-text" in text


def test_configure_idempotent_and_forced(monkeypatch):
    import reporter_tpu.obs.log as log_mod

    monkeypatch.setattr(log_mod, "_configured", False)
    stream_a, stream_b = io.StringIO(), io.StringIO()
    monkeypatch.setenv("REPORTER_LOG_FORMAT", "json")
    monkeypatch.setenv("REPORTER_LOG_LEVEL", "DEBUG")
    saved = logging.getLogger().handlers[:]
    saved_level = logging.getLogger().level
    try:
        obs_log.configure(stream=stream_a)
        assert logging.getLogger().level == logging.DEBUG
        assert isinstance(logging.getLogger().handlers[0].formatter,
                          obs_log.JsonFormatter)
        obs_log.configure(stream=stream_b)  # idempotent: still stream_a
        assert logging.getLogger().handlers[0].stream is stream_a
        obs_log.configure(stream=stream_b, fmt="text", force=True)
        assert logging.getLogger().handlers[0].stream is stream_b
        assert isinstance(logging.getLogger().handlers[0].formatter,
                          obs_log.TextFormatter)
    finally:
        logging.getLogger().handlers[:] = saved
        logging.getLogger().setLevel(saved_level)


# -- overhead: always-on tracing -------------------------------------------


class _StubMatcher:
    backend = "cpu"

    def match_many_async(self, traces):
        results = [{"segments": []} for _ in traces]
        return lambda: results


def test_overhead_with_always_on_spans():
    """The 1k-request ≤10% overhead bound must hold with tracing always on:
    a Span per request riding the batcher plus a flight-recorder decision
    per request, vs the fully uninstrumented span-less path."""
    from reporter_tpu.serve.service import MicroBatcher

    n = 1000
    traces = [{"uuid": "u%d" % i, "trace": []} for i in range(n)]
    rec = FlightRecorder(capacity=64, slow_ms=250.0, sample_every=10)

    def wall(instrument: bool) -> float:
        mb = MicroBatcher(_StubMatcher(), max_batch=64, max_wait_ms=0.0,
                          instrument=instrument)
        t0 = time.perf_counter()
        if instrument:
            spans = [Span("report") for _ in range(n)]
            futures = [mb.submit(t, span=sp) for t, sp in zip(traces, spans)]
            for f, sp in zip(futures, spans):
                f.result()
                sp.finish()
                rec.record(sp)
        else:
            futures = [mb.submit(t) for t in traces]
            for f in futures:
                f.result()
        return time.perf_counter() - t0

    # best-of-5 with an absolute epsilon wide enough for the scheduler
    # jitter a loaded single-CPU full-suite run adds (PR 18 deflake);
    # the 10% relative bound is the documented claim and stands
    t_plain = min(wall(False) for _ in range(5))
    t_traced = min(wall(True) for _ in range(5))
    assert t_traced <= 1.10 * t_plain + 0.075, (t_traced, t_plain)


# -- service end-to-end -----------------------------------------------------


@pytest.fixture(scope="module")
def trace_service():
    from reporter_tpu.matching import MatcherConfig, SegmentMatcher
    from reporter_tpu.serve import ReporterService
    from reporter_tpu.tiles.arrays import build_graph_arrays
    from reporter_tpu.tiles.network import grid_city
    from reporter_tpu.tiles.ubodt import build_ubodt

    city = grid_city(rows=5, cols=5, spacing_m=150.0)
    arrays = build_graph_arrays(city, cell_size=100.0)
    ubodt = build_ubodt(arrays, delta=2000.0)
    matcher = SegmentMatcher(arrays=arrays, ubodt=ubodt, config=MatcherConfig())
    service = ReporterService(matcher, max_wait_ms=5.0)
    httpd = service.make_server("127.0.0.1", 0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    yield "http://127.0.0.1:%d" % httpd.server_port, arrays, service
    httpd.shutdown()


def _street_trace(arrays, n=10):
    nodes = [2 * 5 + c for c in range(5)]
    t = np.linspace(0.05, 0.9, n)
    xs = np.interp(t, np.linspace(0, 1, 5), arrays.node_x[nodes])
    ys = np.interp(t, np.linspace(0, 1, 5), arrays.node_y[nodes])
    lat, lon = arrays.proj.to_latlon(xs, ys)
    return {
        "uuid": "veh-trace",
        "trace": [{"lat": float(a), "lon": float(o), "time": 1000 + 15 * i}
                  for i, (a, o) in enumerate(zip(lat, lon))],
        "match_options": {"mode": "auto", "report_levels": [0, 1],
                          "transition_levels": [0, 1]},
    }


def _post(url, payload, headers=None):
    h = {"Content-Type": "application/json"}
    h.update(headers or {})
    req = urllib.request.Request(url, data=json.dumps(payload).encode(),
                                 headers=h)
    try:
        with urllib.request.urlopen(req, timeout=60) as r:
            return r.status, dict(r.headers), json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), json.loads(e.read().decode())


def _get_traces(url, n=100):
    with urllib.request.urlopen(url + "/debug/traces?n=%d" % n, timeout=30) as r:
        return json.loads(r.read().decode())


def test_client_header_echoed_and_recorded(trace_service):
    """The acceptance path: a tagged request gets the same id echoed and is
    retrievable from GET /debug/traces with a per-stage breakdown."""
    url, arrays, _svc = trace_service
    tid = "acceptance-" + obs_trace.new_trace_id()[:8]
    code, headers, out = _post(url + "/report", _street_trace(arrays),
                               headers={"X-Reporter-Trace": tid})
    assert code == 200
    assert headers.get("X-Reporter-Trace") == tid
    assert "debug" not in out  # always-on tracing does NOT opt the payload in
    # healthy fast traces are tail-sampled 1-in-N; keep posting until this
    # id lands or every-Nth cycles through (bounded)
    found = None
    for _ in range(2 * obs_flight.RECORDER.sample_every):
        entries = _get_traces(url)["traces"]
        found = next((t for t in entries if t["trace_id"] == tid), None)
        if found:
            break
        code, headers, _o = _post(url + "/report", _street_trace(arrays),
                                  headers={"X-Reporter-Trace": tid})
        assert code == 200 and headers.get("X-Reporter-Trace") == tid
    assert found, "tagged trace never surfaced in the flight recorder"
    assert found["status"] == "ok" and found["endpoint"] == "report"
    assert {"queue_wait_s", "device_step_s", "report_fn_s",
            "total_s"} <= set(found["timings"])
    assert found["batch_size"] >= 1


def test_generated_id_echoed_without_header(trace_service):
    url, arrays, _svc = trace_service
    code, headers, _out = _post(url + "/report", _street_trace(arrays))
    assert code == 200
    tid = headers.get("X-Reporter-Trace")
    assert tid and obs_trace.accept_trace_id(tid) == tid


def test_malformed_header_replaced(trace_service):
    url, arrays, _svc = trace_service
    code, headers, _out = _post(url + "/report", _street_trace(arrays),
                                headers={"X-Reporter-Trace": "bad id!!"})
    assert code == 200
    tid = headers.get("X-Reporter-Trace")
    assert tid and tid != "bad id!!"


def test_invalid_request_always_in_recorder(trace_service):
    url, arrays, _svc = trace_service
    tid = "invalid-" + obs_trace.new_trace_id()[:8]
    bad = _street_trace(arrays)
    del bad["uuid"]
    code, headers, out = _post(url + "/report", bad,
                               headers={"X-Reporter-Trace": tid})
    assert code == 400 and headers.get("X-Reporter-Trace") == tid
    entry = next(t for t in _get_traces(url)["traces"]
                 if t["trace_id"] == tid)
    assert entry["status"] == "invalid"
    assert "uuid is required" in entry["error"]


def test_error_request_always_in_recorder(trace_service):
    """A 500 (engine failure) is always retained, whatever the load."""
    url, arrays, svc = trace_service
    tid = "error-" + obs_trace.new_trace_id()[:8]

    class _Boom:
        def match(self, trace, span=None):
            raise RuntimeError("synthetic engine failure")

    real = svc.batcher
    svc.batcher = _Boom()
    try:
        code, headers, out = _post(url + "/report", _street_trace(arrays),
                                   headers={"X-Reporter-Trace": tid})
    finally:
        svc.batcher = real
    assert code == 500 and headers.get("X-Reporter-Trace") == tid
    entry = next(t for t in _get_traces(url)["traces"]
                 if t["trace_id"] == tid)
    assert entry["status"] == "error"
    assert "synthetic engine failure" in entry["error"]


def test_batch_endpoint_traced(trace_service):
    url, arrays, _svc = trace_service
    tid = "batch-" + obs_trace.new_trace_id()[:8]
    code, headers, out = _post(
        url + "/trace_attributes_batch",
        {"traces": [_street_trace(arrays), _street_trace(arrays)]},
        headers={"X-Reporter-Trace": tid})
    assert code == 200 and len(out["results"]) == 2
    assert headers.get("X-Reporter-Trace") == tid


def test_statusz_flight_summary_and_exemplars(trace_service):
    url, arrays, _svc = trace_service
    _post(url + "/report", _street_trace(arrays))
    with urllib.request.urlopen(url + "/statusz", timeout=30) as r:
        out = json.loads(r.read().decode())
    assert out["flight"]["capacity"] >= 1
    assert "sample_every" in out["flight"]
    # the queue-wait histogram carries per-bucket exemplars linking to ids
    qw = out["metrics"]["reporter_microbatch_queue_wait_seconds"]["samples"][0][1]
    assert qw.get("exemplars"), "no exemplars on a served histogram"
    for _i, _v, ex_tid in qw["exemplars"]:
        assert obs_trace.accept_trace_id(ex_tid) == ex_tid


def test_debug_traces_param_validation(trace_service):
    url, _arrays, _svc = trace_service
    code, _h, out = _get_json_code(url + "/debug/traces?n=notanint")
    assert code == 400 and "integer" in out["error"]


def _get_json_code(url):
    try:
        with urllib.request.urlopen(url, timeout=30) as r:
            return r.status, dict(r.headers), json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), json.loads(e.read().decode())


def test_debug_response_carries_trace_id(trace_service):
    url, arrays, _svc = trace_service
    tid = "debug-" + obs_trace.new_trace_id()[:8]
    code, _h, out = _post(url + "/report?debug=1", _street_trace(arrays),
                          headers={"X-Reporter-Trace": tid})
    assert code == 200
    assert out["debug"]["trace_id"] == tid
    assert len(out["debug"]["span_id"]) == 16


# -- trace_top helpers ------------------------------------------------------


def test_trace_top_parse_and_quantiles():
    import importlib.util
    import os

    path = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "trace_top.py")
    spec = importlib.util.spec_from_file_location("trace_top", path)
    tt = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tt)

    text = "\n".join([
        "# HELP t_wait_seconds Wait",
        "# TYPE t_wait_seconds histogram",
        't_wait_seconds_bucket{le="0.01"} 10',
        't_wait_seconds_bucket{le="0.1"} 90',
        't_wait_seconds_bucket{le="+Inf"} 100',
        "t_wait_seconds_sum 5.0",
        "t_wait_seconds_count 100",
        "t_depth 7",
        't_req_total{endpoint="report",outcome="ok"} 42',
    ])
    m = tt.parse_metrics(text)
    assert m["t_depth"][()] == 7
    assert m["t_req_total"][(("endpoint", "report"), ("outcome", "ok"))] == 42
    buckets = tt.hist_buckets(m, "t_wait_seconds")
    assert buckets[-1] == (float("inf"), 100)
    # p50 lands mid second bucket: 0.01 + (50-10)/(90-10)*0.09 = 0.055
    assert tt.hist_quantile(buckets, 0.50) == pytest.approx(0.055)
    # p99 lands in +Inf: clamps to the last finite bound
    assert tt.hist_quantile(buckets, 0.99) == pytest.approx(0.1)
    assert tt.hist_quantile([], 0.5) is None
    # interval deltas: server restart (negative) falls back to cumulative
    prev = [(0.01, 5), (0.1, 20), (float("inf"), 25)]
    d = tt.delta_buckets(buckets, prev)
    assert d == [(0.01, 5), (0.1, 70), (float("inf"), 75)]
    assert tt.delta_buckets(prev, buckets) == prev
    # a frame renders without a live service
    frame = tt.render_frame(m, None, [
        {"trace_id": "abc", "name": "report", "status": "ok",
         "timings": {"queue_wait_s": 0.004, "total_s": 0.31}}], 2.0)
    assert "queue wait" in frame and "abc" in frame


def test_check_metrics_endpoint_sync():
    import importlib.util
    import os

    path = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "check_metrics.py")
    spec = importlib.util.spec_from_file_location("check_metrics_ep", path)
    chk = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(chk)
    actions = chk.served_actions()
    assert "traces" in actions and "report" in actions
    assert actions - chk.documented_actions() == set(), (
        "endpoints missing from docs/http-api.md")
