"""True multi-controller execution: two host processes, one global mesh.

Spawns two fresh Python processes (4 virtual CPU devices each) that
rendezvous through ``jax.distributed`` and run the standard sharded match
program over the combined 8-device mesh, with the per-segment histogram
psum crossing the process boundary (Gloo on CPU; ICI/DCN on TPU pods).
This is the framework's multi-host story actually executing — not a
single-process simulation.
"""

import socket
import subprocess
import sys


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_global_mesh():
    import os

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    port = _free_port()
    env_base = {
        "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
        "JAX_PLATFORMS": "cpu",
        # prepend, don't clobber, and resolve independently of pytest's cwd
        "PYTHONPATH": repo_root + os.pathsep + os.environ.get("PYTHONPATH", ""),
        # silence gloo's per-rank connection chatter
        "GLOO_LOG_LEVEL": "ERROR",
    }

    procs = []
    outs = []
    try:
        for pid in range(2):
            env = dict(os.environ)
            env.update(env_base)
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "reporter_tpu.parallel.multihost",
                 "--coordinator", "127.0.0.1:%d" % port,
                 "--processes", "2", "--process-id", str(pid)],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
            ))
        for p in procs:
            out, _ = p.communicate(timeout=360)
            outs.append(out.decode(errors="replace"))
    finally:
        # a crashed rendezvous must not leak the peer (it would hold the
        # coordinator port and block forever in initialize())
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate(timeout=30)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, "process %d failed:\n%s" % (pid, out[-2000:])
    lines = [
        next(ln for ln in out.splitlines() if ln.startswith("multihost dryrun ok"))
        for out in outs
    ]
    # both controllers computed over the same global mesh: 8 devices, 4
    # local each, and byte-identical globally-reduced results
    assert lines[0] == lines[1]
    assert "8 devices (4 local)" in lines[0]
