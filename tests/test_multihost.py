"""True multi-controller execution: two host processes, one global mesh.

Spawns two fresh Python processes (4 virtual CPU devices each) that
rendezvous through ``jax.distributed`` and run the standard sharded match
program over the combined 8-device mesh, with the per-segment histogram
psum crossing the process boundary (Gloo on CPU; ICI/DCN on TPU pods).
This is the framework's multi-host story actually executing — not a
single-process simulation.
"""

import os
import socket
import subprocess
import sys

import pytest

# capability markers the CHILD processes emit when this jax build cannot
# run the dryrun at all (e.g. jax 0.4.3x: CPU backend without multiprocess
# computations, no jax.shard_map): the dryrun is then unrunnable in THIS
# environment, not broken — skip, the same green-or-skip posture as
# test_parallel.py's shard_map guard.  Any other failure (wrong result,
# crash, rendezvous hang) still fails.
_ENV_UNSUPPORTED_MARKERS = (
    "Multiprocess computations aren't implemented on the CPU backend",
    "module 'jax' has no attribute 'shard_map'",
)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_dryrun_procs(extra_args=()):
    """Spawn the two controller processes, collect their output, and return
    the matching 'multihost dryrun ok' line (asserted byte-identical across
    processes — both must have computed the same globally-reduced result)."""
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    port = _free_port()
    env_base = {
        "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
        "JAX_PLATFORMS": "cpu",
        # prepend, don't clobber, and resolve independently of pytest's cwd
        "PYTHONPATH": repo_root + os.pathsep + os.environ.get("PYTHONPATH", ""),
        # silence gloo's per-rank connection chatter
        "GLOO_LOG_LEVEL": "ERROR",
    }
    procs = []
    outs = []
    try:
        for pid in range(2):
            env = dict(os.environ)
            env.update(env_base)
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "reporter_tpu.parallel.multihost",
                 "--coordinator", "127.0.0.1:%d" % port,
                 "--processes", "2", "--process-id", str(pid),
                 *extra_args],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
            ))
        for p in procs:
            out, _ = p.communicate(timeout=360)
            outs.append(out.decode(errors="replace"))
    finally:
        # a crashed rendezvous must not leak the peer (it would hold the
        # coordinator port and block forever in initialize())
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate(timeout=30)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        if p.returncode != 0:
            for marker in _ENV_UNSUPPORTED_MARKERS:
                if marker in out:
                    pytest.skip("this jax build cannot run the "
                                "multi-controller dryrun: %s" % marker)
        assert p.returncode == 0, "process %d failed:\n%s" % (pid, out[-2000:])
    lines = [
        next(ln for ln in out.splitlines() if ln.startswith("multihost dryrun ok"))
        for out in outs
    ]
    assert lines[0] == lines[1]
    return lines[0]


def test_two_process_global_mesh():
    # both controllers computed over the same global mesh: 8 devices, 4
    # local each, and byte-identical globally-reduced results
    line = _run_dryrun_procs()
    assert "8 devices (4 local, gp 1)" in line


def test_two_process_graph_sharded_mesh_cross_process():
    """gp=8 over the two-process global mesh: with only 4 devices per
    process, an 8-wide gp axis MUST span both processes, so every UBODT
    probe's pmin/pmax collectives cross the process boundary — the
    distributed-table ('DCN on pods') path end to end.  (A gp axis that
    fits inside one host would keep the probe collectives host-local and
    test nothing beyond the dp case.)"""
    line = _run_dryrun_procs(("--graph-devices", "8"))
    assert "gp 8" in line
