#!/usr/bin/env bash
# SLO gating rehearsal (the CI `slo-rehearsal` leg; runnable locally):
# tools/loadgen.py drives the REAL served pipeline open-loop against the
# stated objectives, in two legs:
#
#   1. no-fault: a warmed server must MEET the objectives, and the
#      server's GET /debug/slo verdict must AGREE with loadgen's
#      client-side verdict (loadgen --server-slo exits nonzero on either
#      violation or disagreement).  The artifact must also pass
#      tools/perf_gate.py (schema-complete, like-provenance aware).
#
#   2. injected device_hang (faults.py): the SAME load must VIOLATE the
#      objectives (loadgen rc != 0), and the reported p99 must be
#      demonstrably degraded vs leg 1 — proving coordinated omission is
#      not flattening the tail: latencies are measured against the
#      SCHEDULED send time, so the stall's backlog is in the number even
#      though each post-stall response returns quickly.
#
# Objectives are stated ONCE and identically on both sides: the server
# config's "slo" block and loadgen's --slo-* flags (availability 0.95,
# p99 <= 8000 ms — modest CPU-scale targets; the TPU deployment tightens
# them via the same knobs).
#
# Usage: tests/slo_rehearsal.sh [workdir]
set -euo pipefail

# shared spawn/trap/cleanup/wait helpers (tests/rehearsal_lib.sh): every
# spawned server is tracked and cleaned on EVERY exit path with SIGKILL
# escalation — a failed leg must not strand a listener that poisons
# later CI legs on the same runner
. "$(dirname "$0")/rehearsal_lib.sh"
reh_init "${1:-}" reporter-slo
PORT=18061
PORT2=18062
# the fleet-economics plane rides along (docs/economics.md): history on
# so /debug/history has a ring to serve, and its dump + /debug/cost land
# in $WORK with the other uploaded artifacts
export REPORTER_HISTORY_DIR="$WORK/history"
echo "slo rehearsal workdir: $WORK"

# one length bucket (every loadgen window is 16 points) keeps the warmup
# grid small enough that --warmup boots in CI time
cat > "$WORK/config.json" <<EOF
{
  "network": {"type": "grid", "rows": 8, "cols": 8, "spacing_m": 200},
  "matcher": {"sigma_z": 4.07, "beta": 3.0, "search_radius": 50.0,
              "length_buckets": [16]},
  "backend": "jax",
  "batch": {"max_batch": 64, "max_wait_ms": 5},
  "slo": {"window_s": 120, "availability": 0.95,
          "latency": {"*": {"p99_ms": 8000}}}
}
EOF

LOADGEN_ARGS=(
    --rate 15 --duration 6 --vehicles 12 --points 32 --window 16 --grid 8
    --seed 7 --concurrency 24 --timeout-s 8
    --slo-availability 0.95 --slo-p99-ms 8000
)

# ---- leg 1: no fault — objectives hold, verdicts agree -------------------
echo "== leg 1: no-fault (warmed serve, verdicts must agree) =="
python -m reporter_tpu.serve --warmup "$WORK/config.json" "127.0.0.1:$PORT" \
    > "$WORK/serve_nofault.log" 2>&1 &
SERVE_PID=$!
reh_track "$SERVE_PID"
if ! reh_wait_replica "http://127.0.0.1:$PORT" 240; then
    echo "FAIL: no-fault service never came up; tail of serve log:"
    tail -20 "$WORK/serve_nofault.log"
    exit 1
fi

python tools/loadgen.py --url "http://127.0.0.1:$PORT" \
    "${LOADGEN_ARGS[@]}" --server-slo \
    --out "$WORK/loadgen_nofault.json"
echo "no-fault leg: objectives met, client and server verdicts agree"

# the artifact is consumable by the perf gate (schema + provenance rules)
python tools/perf_gate.py BENCH_r0*.json \
    --fresh "$WORK/loadgen_nofault.json" \
    > "$WORK/perf_gate_loadgen.json"
echo "loadgen artifact accepted by tools/perf_gate.py"

# the economics surfaces ride the uploaded artifacts: the live cost
# ledger and the demand-history window the run just wrote (CI uploads
# $WORK wholesale), plus the artifact's own measured cost block
curl -fsS "http://127.0.0.1:$PORT/debug/cost" > "$WORK/debug_cost.json"
curl -fsS "http://127.0.0.1:$PORT/debug/history?window=600" \
    > "$WORK/debug_history.json"
python - "$WORK" <<'EOF'
import json, sys

work = sys.argv[1]
cost = json.load(open(work + "/debug_cost.json"))
assert cost["chip_seconds"]["total"] > 0, cost
hist = json.load(open(work + "/debug_history.json"))
assert hist["enabled"] and hist["n"] > 0, hist
art = json.load(open(work + "/loadgen_nofault.json"))
assert art["cost"]["source"] == "server", art.get("cost")
print("economics artifacts: %.1f chip-s accrued, %d history ticks, "
      "loadgen cost block source=server"
      % (cost["chip_seconds"]["total"], hist["n"]))
EOF

kill "$SERVE_PID" 2>/dev/null || true
wait "$SERVE_PID" 2>/dev/null || true

# ---- leg 2: device_hang — the tail must show, the gate must trip ---------
echo "== leg 2: injected device_hang (tail must be visible, SLO must fail) =="
REPORTER_FAULT_DEVICE_HANG="2.5" \
python -m reporter_tpu.serve "$WORK/config.json" "127.0.0.1:$PORT2" \
    > "$WORK/serve_hang.log" 2>&1 &
SERVE_PID=$!
reh_track "$SERVE_PID"
if ! reh_wait_replica "http://127.0.0.1:$PORT2" 240; then
    echo "FAIL: hang-leg service never came up; tail of serve log:"
    tail -20 "$WORK/serve_hang.log"
    exit 1
fi

set +e
python tools/loadgen.py --url "http://127.0.0.1:$PORT2" \
    "${LOADGEN_ARGS[@]}" \
    --out "$WORK/loadgen_hang.json"
HANG_RC=$?
set -e
if [ "$HANG_RC" -eq 0 ]; then
    echo "FAIL: loadgen passed the SLO under an injected device hang"
    exit 1
fi
if [ ! -s "$WORK/loadgen_hang.json" ]; then
    echo "FAIL: hang leg produced no artifact (rc $HANG_RC was not a verdict)"
    exit 1
fi

python - "$WORK" <<'EOF'
# coordinated omission is not hiding the tail: the hang run's
# scheduled-time p99 carries the injected stalls' backlog
import json, sys

work = sys.argv[1]
nofault = json.load(open(work + "/loadgen_nofault.json"))
hang = json.load(open(work + "/loadgen_hang.json"))
p99_nofault = nofault["quantiles"]["p99_ms"]
p99_hang = hang["quantiles"]["p99_ms"]
gap_p99 = hang["service_time_quantiles"]["p99_ms"]
assert p99_hang is not None and p99_nofault is not None
floor = max(2500.0, 1.5 * p99_nofault)
assert p99_hang >= floor, (
    "hang p99 %.0f ms below %.0f ms: the injected 2.5 s stalls are not "
    "in the tail — coordinated omission?" % (p99_hang, floor))
assert hang["slo"]["client"]["ok"] is False
print("p99 no-fault %.0f ms -> hang %.0f ms (send-to-response view: "
      "%.0f ms); SLO verdict: violating, rc nonzero — gate works"
      % (p99_nofault, p99_hang, gap_p99))
EOF

echo "slo rehearsal OK (artifacts in $WORK)"
