import numpy as np
import pytest

from reporter_tpu import geo
from reporter_tpu.tiles.network import RoadNetwork, Edge, grid_city
from reporter_tpu.tiles.arrays import build_graph_arrays
from reporter_tpu.tiles.segment_id import unpack_segment_id


@pytest.fixture(scope="module")
def city():
    return grid_city(rows=6, cols=6, spacing_m=200.0)


@pytest.fixture(scope="module")
def arrays(city):
    return build_graph_arrays(city, cell_size=100.0)


def test_grid_city_shape(city):
    assert city.num_nodes == 36
    # 6 rows * 5 blocks + 6 cols * 5 blocks, 2 directed edges each
    assert city.num_edges == 2 * (6 * 5 + 6 * 5)
    # all segment ids valid and level-consistent
    for e in city.edges:
        level, _, _ = unpack_segment_id(e.segment_id)
        assert level == e.level


def test_edge_lengths_close_to_spacing(city, arrays):
    np.testing.assert_allclose(arrays.edge_len, 200.0, rtol=5e-3)


def test_segment_table(city, arrays):
    # one segment per directed edge in the default grid
    assert len(arrays.seg_ids) == city.num_edges
    assert (arrays.edge_seg >= 0).all()
    np.testing.assert_allclose(arrays.seg_len[arrays.edge_seg], arrays.edge_len, rtol=1e-6)
    assert (arrays.edge_seg_off == 0).all()


def test_multi_edge_segments():
    city = grid_city(rows=2, cols=5, spacing_m=100.0, two_edge_segments=True)
    arrays = build_graph_arrays(city, cell_size=100.0)
    multi = {}
    for ei in range(arrays.num_edges):
        s = int(arrays.edge_seg[ei])
        multi.setdefault(s, []).append(ei)
    spans = [eids for eids in multi.values() if len(eids) > 1]
    assert spans, "expected some multi-edge segments"
    for eids in spans:
        offs = sorted(float(arrays.edge_seg_off[e]) for e in eids)
        assert offs[0] == 0.0 and offs[1] > 0.0
        s = int(arrays.edge_seg[eids[0]])
        total = sum(float(arrays.edge_len[e]) for e in eids)
        assert arrays.seg_len[s] == pytest.approx(total, rel=1e-6)


def test_csr_adjacency(city, arrays):
    for n in range(city.num_nodes):
        eids = arrays.out_edges[arrays.out_start[n]:arrays.out_start[n + 1]]
        assert all(arrays.edge_from[e] == n for e in eids)
    assert arrays.out_start[-1] == city.num_edges


def test_spatial_grid_covers_all_segments(arrays):
    present = set(arrays.grid_items[arrays.grid_items >= 0].tolist())
    assert present == set(range(len(arrays.shp_ax)))


def test_grid_query_finds_nearby_segment(city, arrays):
    # a point 10 m off the middle of the first edge must appear in the 3x3
    # neighbourhood of its cell
    si = 0
    mx = (arrays.shp_ax[si] + arrays.shp_bx[si]) / 2
    my = (arrays.shp_ay[si] + arrays.shp_by[si]) / 2 + 10.0
    cx, cy = arrays.cell_of(float(mx), float(my))
    items = set()
    for dy in (-1, 0, 1):
        for dx in (-1, 0, 1):
            cell = (cy + dy) * arrays.grid_nx + (cx + dx)
            if 0 <= cell < arrays.grid_items.shape[0]:
                items.update(arrays.grid_items[cell][arrays.grid_items[cell] >= 0].tolist())
    assert si in items


def test_roundtrip_dict(city):
    d = city.to_dict()
    net2 = RoadNetwork.from_dict(d)
    assert net2.num_nodes == city.num_nodes
    assert net2.num_edges == city.num_edges
    assert net2.edges[3].segment_id == city.edges[3].segment_id


def test_device_graph_pytree(arrays):
    import jax

    dg = arrays.to_device()
    leaves = jax.tree_util.tree_leaves(dg)
    assert all(hasattr(l, "shape") for l in leaves)
    # cell-major candidate rows: rank-2 with a 8-lane record per grid slot
    n_cells, cap = arrays.grid_items.shape
    assert dg.cell_rows.shape == (n_cells, cap * 8)


def test_device_leaves_tpu_layout_friendly(arrays):
    """TPU layouts tile the two minor dims of every array to (8, 128); a
    rank-3 leaf with small minor dims pads catastrophically (a
    [buckets, 2, 8] table would pad 64x in HBM).  Invariants: no device
    leaf above rank 2, and the hot-table minor dims are exact lane rows."""
    import jax

    from reporter_tpu.tiles.ubodt import build_ubodt

    dg = arrays.to_device()
    du = build_ubodt(arrays, delta=500.0).to_device()
    for leaf in jax.tree_util.tree_leaves(dg) + jax.tree_util.tree_leaves(du):
        assert leaf.ndim <= 2, leaf.shape
    assert du.packed.shape[1] == 128  # one bucket == one 512-byte lane row
    assert dg.edge_rows.shape[1] == 8
    assert dg.cell_rows.shape[1] % 8 == 0
