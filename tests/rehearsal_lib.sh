# tests/rehearsal_lib.sh — the spawn/trap/cleanup/wait boilerplate every
# rehearsal shares (fleet / slo / session / e2e / overload), factored so
# a new leg cannot re-invent a cleanup path that strands a listener.
#
# Source AFTER `set -euo pipefail`:
#
#   . "$(dirname "$0")/rehearsal_lib.sh"
#   reh_init "${1:-}" reporter-myleg     # cds to repo root, sets $WORK,
#                                        # installs the EXIT cleanup trap
#   reh_track "$PID"                     # plain child: TERM, wait, KILL
#   reh_track_watcher "$PID"             # sampler loop: KILL immediately
#   reh_track_fleet "$PID" "$WORK"       # tools/fleet.py supervisor: TERM
#                                        # + escalation + fleet.json pid
#                                        # sweep (router/replica strays)
#   reh_wait_replica URL TRIES [warmed]  # /health 200 + attached backend
#                                        # (+ warmup finished with arg 3)
#   reh_wait_fleet ROUTER_URL N BASE_PORT COUNT TRIES [warmed]
#                                        # every replica attached AND the
#                                        # router reporting N available
#
# Every tracked pid is cleaned on EVERY exit path with SIGKILL
# escalation — a failed leg must not poison later CI legs on the same
# runner.

REH_PIDS=()
REH_WATCHER_PIDS=()
REH_FLEET_PID=""
REH_FLEET_WORK=""

reh_init() {
    cd "$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
    export PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}"
    export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
    local prefix="${2:-reporter-rehearsal}"
    WORK="${1:-$(mktemp -d "/tmp/${prefix}.XXXXXX")}"
    mkdir -p "$WORK"
    trap reh_cleanup EXIT
}

reh_track() { REH_PIDS+=("$1"); }
reh_track_watcher() { REH_WATCHER_PIDS+=("$1"); }
reh_track_fleet() { REH_FLEET_PID="$1"; REH_FLEET_WORK="$2"; }

reh_untrack_watchers() {
    local pid
    for pid in ${REH_WATCHER_PIDS[@]+"${REH_WATCHER_PIDS[@]}"}; do
        kill -9 "$pid" 2>/dev/null || true
    done
    REH_WATCHER_PIDS=()
}

# Gracefully stop the tracked fleet supervisor and REQUIRE exit 0
# (the drain contract); clears the tracking so reh_cleanup skips it.
reh_stop_fleet() {
    [ -n "$REH_FLEET_PID" ] || return 0
    local pid="$REH_FLEET_PID" rc
    kill "$pid" 2>/dev/null || true
    set +e
    wait "$pid"
    rc=$?
    set -e
    REH_FLEET_PID=""
    if [ "$rc" != 0 ]; then
        echo "FAIL: fleet supervisor exited rc $rc on drain; log tail:"
        tail -30 "$REH_FLEET_WORK/fleet.log" 2>/dev/null || true
        return 1
    fi
    return 0
}

reh_cleanup() {
    local pid
    reh_untrack_watchers
    if [ -n "$REH_FLEET_PID" ] && kill -0 "$REH_FLEET_PID" 2>/dev/null; then
        kill "$REH_FLEET_PID" 2>/dev/null || true
        for _ in $(seq 1 40); do
            kill -0 "$REH_FLEET_PID" 2>/dev/null || break
            sleep 0.5
        done
        kill -9 "$REH_FLEET_PID" 2>/dev/null || true
    fi
    # belt-and-braces: any replica/router pid still in the state file
    if [ -n "$REH_FLEET_WORK" ] && [ -f "$REH_FLEET_WORK/fleet.json" ]; then
        python - "$REH_FLEET_WORK/fleet.json" <<'EOF' 2>/dev/null || true
import json, os, signal, sys
state = json.load(open(sys.argv[1]))
pids = [state.get("router", {}).get("pid")] + [
    r.get("pid") for r in state.get("replicas", [])]
for pid in pids:
    if pid:
        try:
            os.kill(pid, signal.SIGKILL)
        except OSError:
            pass
EOF
    fi
    for pid in ${REH_PIDS[@]+"${REH_PIDS[@]}"}; do
        kill "$pid" 2>/dev/null || true
    done
    for pid in ${REH_PIDS[@]+"${REH_PIDS[@]}"}; do
        for _ in $(seq 1 20); do
            kill -0 "$pid" 2>/dev/null || break
            sleep 0.5
        done
        kill -9 "$pid" 2>/dev/null || true
        wait "$pid" 2>/dev/null || true
    done
}

# reh_wait_replica URL TRIES [warmed] — /health 200 "ok" with an
# attached backend; pass a third arg to also require the warmup pass to
# have finished (warming false)
reh_wait_replica() {
    local url="$1" tries="$2" warmed="${3:-}"
    for _ in $(seq 1 "$tries"); do
        REH_URL="$url" REH_WARMED="$warmed" python - <<'EOF' && return 0 || sleep 1
import json, os, sys, urllib.request
try:
    h = json.load(urllib.request.urlopen(
        os.environ["REH_URL"] + "/health", timeout=2))
except Exception:
    sys.exit(1)
ok = h.get("status") == "ok" and bool(h.get("backend"))
if os.environ.get("REH_WARMED"):
    ok = ok and not h.get("warming")
sys.exit(0 if ok else 1)
EOF
    done
    return 1
}

# reh_wait_fleet ROUTER_URL N_AVAILABLE BASE_PORT COUNT TRIES [warmed]
# — every replica on BASE_PORT..BASE_PORT+COUNT-1 attached (and warmed
# with arg 6), and the router reporting N_AVAILABLE available
reh_wait_fleet() {
    local router="$1" n="$2" base="$3" count="$4" tries="$5" warmed="${6:-}"
    REH_ROUTER="$router" REH_N="$n" REH_BASE="$base" REH_COUNT="$count" \
        REH_TRIES="$tries" REH_WARMED="$warmed" python - <<'EOF'
import json, os, sys, time, urllib.request

router = os.environ["REH_ROUTER"]
n = int(os.environ["REH_N"])
base = int(os.environ["REH_BASE"])
count = int(os.environ["REH_COUNT"])
tries = int(os.environ["REH_TRIES"])
warmed = bool(os.environ.get("REH_WARMED"))

def up(url, need_backend):
    try:
        h = json.load(urllib.request.urlopen(url + "/health", timeout=2))
    except Exception:
        return False
    if need_backend:
        ok = h.get("status") == "ok" and bool(h.get("backend"))
        return ok and not (warmed and h.get("warming"))
    return h.get("available") == n

replicas = ["http://127.0.0.1:%d" % (base + i) for i in range(count)]
deadline = time.monotonic() + tries
while time.monotonic() < deadline:
    if all(up(u, True) for u in replicas) and up(router, False):
        sys.exit(0)
    time.sleep(1)
sys.exit(1)
EOF
}
