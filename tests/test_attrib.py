"""Named-stage device-time attribution (reporter_tpu/obs/attrib.py).

Three layers, all chip-free:

  * the trace-event parser driven end-to-end by a checked-in synthetic
    TPU profile (tests/fixtures/attrib_trace.json) — stage table, legacy
    per-file/module groupings, and the CPU hlo_op->stage bridge;
  * the shared roofline/row accounting against ops/hashtable's own
    dedup constants;
  * the live capture round-trip on the CPU backend: a real matcher's
    dispatches profiled, parsed, and served — gauges, /statusz summary,
    /debug/attrib (incl. the single-flight 409 carrying the in-flight
    trace_id), and the differential guarantee that annotated kernels are
    bit-identical to unannotated ones.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from reporter_tpu.obs import attrib, profiler
from reporter_tpu.obs import metrics as obs_metrics

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                       "attrib_trace.json")


# ---------------------------------------------------------------------------
# parser, on the synthetic TPU fixture


class TestParser:
    def test_fixture_stage_table(self):
        out = attrib.parse_trace_file(FIXTURE)
        assert out["platform"] == "tpu"
        assert out["devices"] == 1
        assert out["device_total_ms"] == pytest.approx(4.5)
        assert out["stages_ms"] == {
            "candidate-sweep": pytest.approx(2.0),
            "ubodt-probe": pytest.approx(1.5),  # incl. the args-less repeat
            "select": pytest.approx(0.5),
            "scan-recursion": pytest.approx(0.25),
            attrib.UNATTRIBUTED: pytest.approx(0.25),
        }
        # every named stage the parser found is a canonical scope label
        assert set(out["stages_ms"]) - {attrib.UNATTRIBUTED} <= set(attrib.STAGES)

    def test_fixture_legacy_groupings(self):
        out = attrib.parse_trace_file(FIXTURE)
        # module time comes from the "XLA Modules" thread, outside the total
        assert out["by_module_ms"] == {"jit_fn": pytest.approx(4.5)}
        assert out["by_file_ms"]["candidates.py"] == pytest.approx(2.0)
        assert out["by_file_ms"]["hashtable.py"] == pytest.approx(2.0)
        assert out["by_file_ms"]["(no source)"] == pytest.approx(0.5)
        assert out["top_lines_ms"]["reporter_tpu/ops/candidates.py:104"] == \
            pytest.approx(2.0)

    def test_innermost_scope_wins(self):
        # nested scopes (transition-build > ubodt-probe) attribute to the
        # innermost label — fusion.2's path carries both
        out = attrib.parse_trace_file(FIXTURE)
        assert "transition-build" not in out["stages_ms"]
        assert out["stages_ms"]["ubodt-probe"] > 0

    def test_cpu_events_via_op_stage_map(self):
        events = [
            {"ph": "M", "pid": 1, "name": "process_name",
             "args": {"name": "python"}},
            {"ph": "X", "pid": 1, "tid": 7, "name": "gather_fusion",
             "dur": 3000, "args": {"hlo_module": "jit_fn",
                                   "hlo_op": "gather_fusion"}},
            {"ph": "X", "pid": 1, "tid": 7, "name": "dot.17", "dur": 1000,
             "args": {"hlo_module": "jit_fn", "hlo_op": "dot.17"}},
            {"ph": "X", "pid": 1, "tid": 7, "name": "mystery.1", "dur": 500,
             "args": {"hlo_module": "jit_fn", "hlo_op": "mystery.1"}},
            # module-level executor events carry no hlo_op: excluded
            {"ph": "X", "pid": 1, "tid": 7, "name": "ThunkExecutor::Execute",
             "dur": 99999},
        ]
        m = {("jit_fn", "gather_fusion"): "ubodt-probe", "dot.17": "select"}
        out = attrib.parse_trace_events(events, m)
        assert out["platform"] == "cpu"
        assert out["device_total_ms"] == pytest.approx(4.5)
        assert out["stages_ms"] == {
            "ubodt-probe": pytest.approx(3.0),
            "select": pytest.approx(1.0),
            attrib.UNATTRIBUTED: pytest.approx(0.5),
        }

    def test_op_stage_map_from_hlo(self):
        txt = """HloModule jit_fn, entry_computation_layout={()->f32[]}
  %gather_fusion = f32[8]{0} fusion(), kind=kLoop, metadata={op_name="jit(fn)/jit(main)/rs.ubodt-probe/gather" source_file="x.py"}
  ROOT %dot.17 = f32[] dot(), metadata={op_name="jit(fn)/rs.candidate-sweep/rs.select/dot_general"}
  %plain.1 = f32[] add(), metadata={op_name="jit(fn)/add"}
"""
        m = attrib.op_stage_map_from_hlo([txt])
        assert m[("jit_fn", "gather_fusion")] == "ubodt-probe"
        assert m["dot.17"] == "select"  # innermost of the nested path
        assert "plain.1" not in m

    def test_parse_dir_merges(self, tmp_path):
        d = tmp_path / "cap" / "plugins" / "profile" / "t1"
        d.mkdir(parents=True)
        with open(FIXTURE) as f:
            tr = json.load(f)
        (d / "a.trace.json").write_text(json.dumps(tr))
        (d / "b.trace.json").write_text(json.dumps(tr))
        out = attrib.parse_trace_dir(str(tmp_path / "cap"))
        assert out["devices"] == 2
        assert out["device_total_ms"] == pytest.approx(9.0)
        assert out["stages_ms"]["candidate-sweep"] == pytest.approx(4.0)

    def test_parse_dir_empty_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            attrib.parse_trace_dir(str(tmp_path))

    def test_trace_analyze_keeps_output_format(self):
        import importlib.util

        path = os.path.join(os.path.dirname(__file__), "..", "tools",
                            "trace_analyze.py")
        spec = importlib.util.spec_from_file_location("trace_analyze", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        out = mod.analyze(FIXTURE)
        # the historical keys survive, stages_ms rides along
        for key in ("path", "devices", "device_total_ms", "by_module_ms",
                    "by_file_ms", "top_lines_ms", "stages_ms"):
            assert key in out, key


# ---------------------------------------------------------------------------
# shared roofline / row accounting


class TestAccounting:
    def test_dedup_budget_matches_hashtable(self):
        from reporter_tpu.ops.hashtable import (
            _DEDUP_CAP_RATIO, _DEDUP_MIN_PAIRS)

        for n in (100, 1024, 10_000, 2_000_000):
            assert attrib.dedup_budget(n) == max(
                _DEDUP_MIN_PAIRS // 2, n // _DEDUP_CAP_RATIO)

    def test_executed_rows(self):
        n = 512 * 63 * 8 * 8
        assert attrib.executed_rows(n, 2) == 2 * n
        assert attrib.executed_rows(n, 1) == n
        assert attrib.executed_rows(n, 2, dedup=True) == \
            2 * attrib.dedup_budget(n)
        # the bench fleet numbers from docs/measurements (4.13M -> 1.03M)
        assert attrib.executed_rows(n, 2) == 4_128_768
        assert attrib.executed_rows(n, 1, dedup=True) == 1_032_192

    def test_roofline_block(self):
        from reporter_tpu.tiles.ubodt import ROW_W

        blk = attrib.roofline_block(
            512, 64, 8, 1.0, bucket_entries=16, max_probes=2, grid_cap=32,
            hbm_gbs=819.0)
        pairs = 512 * 63 * 64
        expect_bytes = pairs * 2 * 16 * ROW_W * 4 + 512 * 64 * 4 * 32 * 32
        assert blk["est_gather_gb_per_s"] == pytest.approx(
            expect_bytes / 1e9, rel=0.01)
        assert blk["hbm_frac"] == pytest.approx(
            expect_bytes / 1e9 / 819.0, abs=1e-3)
        assert blk["rows_per_rep"] == 2 * pairs
        dblk = attrib.roofline_block(
            512, 64, 8, 1.0, bucket_entries=32, max_probes=1, grid_cap=32,
            dedup=True)
        assert dblk["rows_per_rep"] == attrib.dedup_budget(pairs)


# ---------------------------------------------------------------------------
# live capture round-trip on the CPU backend (no chip required)


@pytest.fixture(scope="module")
def tiny_matcher():
    from reporter_tpu.matching import MatcherConfig, SegmentMatcher
    from reporter_tpu.tiles.network import grid_city

    return SegmentMatcher(network=grid_city(rows=4, cols=4, spacing_m=200.0),
                          config=MatcherConfig())


class TestCaptureRoundTrip:
    def test_capture_matcher_stage_table(self, tiny_matcher):
        res = attrib.capture_matcher(tiny_matcher, reps=2)
        assert res["platform"] == "cpu"
        assert res["device_total_ms"] > 0
        named = set(res["stages_ms"]) - {attrib.UNATTRIBUTED}
        # the CPU bridge resolved real named stages, and every name is a
        # canonical jax.named_scope label
        assert named, "no stage resolved — the op->stage bridge broke"
        assert named <= set(attrib.STAGES)
        assert {"candidate-sweep", "ubodt-probe"} & named
        # published: gauges + age + the /statusz summary line
        snap = obs_metrics.REGISTRY.snapshot()
        stage_samples = dict(
            (tuple(lv), v) for lv, v in
            snap["reporter_stage_device_seconds"]["samples"])
        for name in named:
            assert stage_samples[(name,)] == pytest.approx(
                res["stages_ms"][name] / 1e3)
        [(_, age)] = snap["reporter_attrib_age_seconds"]["samples"]
        assert 0 <= age < 120
        summ = attrib.summary()
        assert summ["captured"] and summ["platform"] == "cpu"
        assert summ["top_stage"]["stage"] in attrib.STAGES

    def test_lower_text_bypasses_and_restores_compilation_cache(self,
                                                                tiny_matcher):
        """The op->stage bridge must compile OUTSIDE the persistent cache
        (jax's cache key ignores metadata, so a warm cache replays
        pre-annotation executables with no stage labels) and must restore
        the config afterwards."""
        import jax

        import jax.numpy as jnp

        prev = jax.config.jax_compilation_cache_dir
        fn = tiny_matcher._get_jit("compact", "scan")
        cargs = (tiny_matcher._dg, tiny_matcher._du,
                 jnp.zeros((4, 1, 16), jnp.float32), tiny_matcher._params,
                 tiny_matcher.cfg.beam_k)
        try:
            jax.config.update("jax_compilation_cache_dir", "/tmp/attrib_cc")
            txt = attrib._lower_text(fn, attrib._abstract_args(cargs))
            assert txt and attrib.STAGE_PREFIX + "candidate-sweep" in txt
            assert jax.config.jax_compilation_cache_dir == "/tmp/attrib_cc"
        finally:
            jax.config.update("jax_compilation_cache_dir", prev)

    def test_matcher_registered_programs(self, tiny_matcher):
        tiny_matcher.match_many(tiny_matcher.dummy_traces(16, 1))
        labels = attrib.registered_program_labels()
        assert any(lbl.endswith(":scan") for lbl in labels)

    def test_stale_stage_gauges_zeroed(self):
        attrib.store_result({"captured_unix": time.time(),
                             "stages_ms": {"select": 3.0}})
        attrib.store_result({"captured_unix": time.time(),
                             "stages_ms": {"backtrace": 1.0}})
        snap = obs_metrics.REGISTRY.snapshot()
        samples = dict((tuple(lv), v) for lv, v in
                       snap["reporter_stage_device_seconds"]["samples"])
        assert samples[("select",)] == 0.0
        assert samples[("backtrace",)] == pytest.approx(0.001)

    def test_single_flight_busy_carries_trace_id(self, tiny_matcher):
        entered = threading.Event()
        release = threading.Event()

        def hold():
            with profiler.session("profile", trace_id="owner-123",
                                  seconds=1.0):
                entered.set()
                release.wait(10)

        t = threading.Thread(target=hold, daemon=True)
        t.start()
        assert entered.wait(10)
        try:
            with pytest.raises(profiler.ProfilerBusy) as ei:
                attrib.capture_matcher(tiny_matcher, reps=1)
            assert ei.value.inflight["trace_id"] == "owner-123"
            assert ei.value.inflight["kind"] == "profile"
        finally:
            release.set()
            t.join(10)

    def test_age_gauge_minus_one_before_any_capture(self):
        # a fresh registry collector run with no capture reports -1
        saved = attrib._LAST
        try:
            attrib._LAST = None
            attrib._update_age()
            assert attrib.G_ATTRIB_AGE.value == -1.0
        finally:
            attrib._LAST = saved
            attrib._update_age()


class TestDifferential:
    def test_annotated_bit_identical_to_unannotated(self, tiny_matcher,
                                                    monkeypatch):
        """The acceptance differential: kernels with scope annotation
        emit bit-identical outputs to unannotated ones, both viterbi
        forwards, dedup on."""
        import functools

        import jax
        import jax.numpy as jnp

        from reporter_tpu.ops import viterbi as vt

        m = tiny_matcher
        rng = np.random.default_rng(0)
        B, T = 4, 32
        x0 = float(np.mean(m.arrays.node_x))
        y0 = float(np.mean(m.arrays.node_y))
        px = (x0 + rng.normal(0, 60, (B, T)).cumsum(1)).astype(np.float32)
        py = (y0 + rng.normal(0, 60, (B, T)).cumsum(1)).astype(np.float32)
        tm = np.arange(T, dtype=np.float32)[None].repeat(B, 0) * 5
        valid = np.ones((B, T), np.float32)
        valid[:, T - 3:] = 0  # padded tail
        xin = jnp.asarray(vt.pack_inputs(px, py, tm, valid))

        for kernel in ("scan", "assoc"):
            outs = {}
            for flag in ("1", "0"):
                monkeypatch.setenv("REPORTER_STAGE_SCOPES", flag)
                fn = jax.jit(functools.partial(
                    vt.match_batch_compact_packed, kernel=kernel, dedup=True),
                    static_argnums=(4,))
                outs[flag] = np.asarray(
                    fn(m._dg, m._du, xin, m._params, m.cfg.beam_k))
            assert np.array_equal(outs["1"], outs["0"]), kernel


class TestServiceEndpoints:
    @pytest.fixture(scope="class")
    def service(self, tiny_matcher):
        from reporter_tpu.serve import ReporterService

        return ReporterService(tiny_matcher, max_wait_ms=2.0)

    def test_debug_attrib_get_serves_last(self, service):
        attrib.store_result({"captured_unix": time.time(),
                             "platform": "cpu", "device_total_ms": 1.0,
                             "stages_ms": {"select": 1.0}})
        code, out = service.handle_attrib({})
        assert code == 200
        assert out["attrib"]["stages_ms"] == {"select": 1.0}
        assert out["summary"]["captured"] is True

    def test_debug_attrib_capture_on_demand(self, service):
        code, out = service.handle_attrib({"capture": ["1"], "reps": ["1"]})
        assert code == 200
        named = set(out["attrib"]["stages_ms"]) - {attrib.UNATTRIBUTED}
        assert named and named <= set(attrib.STAGES)

    def test_debug_attrib_busy_409(self, service):
        entered = threading.Event()
        release = threading.Event()

        def hold():
            with profiler.session("attrib", trace_id="cap-owner"):
                entered.set()
                release.wait(10)

        t = threading.Thread(target=hold, daemon=True)
        t.start()
        assert entered.wait(10)
        try:
            code, out = service.handle_attrib(
                {"capture": ["1"], "reps": ["1"]})
            assert code == 409
            assert out["inflight"]["trace_id"] == "cap-owner"
            # the /debug/profile single-flight shares the same guard and
            # names the same owner
            code, out = service.handle_profile({"seconds": ["0.05"]})
            assert code == 409
            assert out["inflight"]["trace_id"] == "cap-owner"
        finally:
            release.set()
            t.join(10)

    def test_debug_attrib_bad_reps(self, service):
        code, out = service.handle_attrib({"capture": ["1"], "reps": ["x"]})
        assert code == 400

    def test_statusz_carries_attrib_summary(self, service):
        code, out = service.handle_statusz()
        assert code == 200
        assert "attrib" in out
        assert "last_onchip" in out["attrib"]
        # the provenance block (this repo has on-chip measurements banked)
        assert out["attrib"]["last_onchip"]["file"].startswith(
            "docs/measurements/")
