"""Packer equivalence suite: the columnar host data plane must be
BIT-IDENTICAL to the legacy per-trace loop (docs/performance.md "The
columnar host data plane").

matching/columnar.py replaces matcher._fill_rows' per-row Python with one
batched projection + one fancy-indexed scatter per column.  That swap is
only allowed to be a perf change: every padded array, every carried times
list, and every wire-format match result must equal the legacy path's
exactly — across both viterbi kernels, both UBODT layouts, the sparse
model, and the session path.  ``REPORTER_HOST_PACK=0`` /
``MatcherConfig(host_pack=False)`` is the differential reference.
"""

import json

import numpy as np
import pytest

from reporter_tpu.matching import MatcherConfig, SegmentMatcher
from reporter_tpu.matching import columnar
from reporter_tpu.synth import TraceSynthesizer
from reporter_tpu.tiles.arrays import build_graph_arrays
from reporter_tpu.tiles.network import grid_city
from reporter_tpu.tiles.ubodt import build_ubodt

MO = {"mode": "auto", "report_levels": [0, 1], "transition_levels": [0, 1]}


@pytest.fixture(autouse=True)
def _no_ambient_host_pack(monkeypatch):
    """This suite drives host_pack per-matcher through MatcherConfig; an
    ambient REPORTER_HOST_PACK (e.g. the CI host-pipeline job forcing the
    legacy packer suite-wide) would override both sides of every
    differential and make them vacuous.  test_env_knob_overrides_config
    sets the env explicitly on top of this."""
    monkeypatch.delenv("REPORTER_HOST_PACK", raising=False)


@pytest.fixture(scope="module")
def setup():
    city = grid_city(rows=6, cols=6, spacing_m=150.0)
    arrays = build_graph_arrays(city, cell_size=100.0)
    ubodt = build_ubodt(arrays, delta=1500.0)
    return arrays, ubodt


@pytest.fixture(scope="module")
def matcher(setup):
    arrays, ubodt = setup
    return SegmentMatcher(arrays=arrays, ubodt=ubodt,
                          config=MatcherConfig(length_buckets=[16, 64]))


def _traces(arrays, b, t, seed=7, dt=5.0):
    synth = TraceSynthesizer(arrays, seed=seed)
    return [s.trace for s in synth.batch(b, t, dt=dt, sigma=3.0)]


def _varied_traces(arrays, seed=3, dt=5.0):
    """Ragged lengths + int/float/mixed time typing — the shapes the
    packer's scatter indexing has to get exactly right."""
    synth = TraceSynthesizer(arrays, seed=seed)
    lens = [1, 2, 5, 16, 9, 3, 12, 7]
    out = []
    for i, n in enumerate(lens):
        tr = synth.synthesize(n_points=n, uuid="veh-%d" % i, dt=dt).trace
        for j, p in enumerate(tr["trace"]):
            if i % 3 == 0:
                p["time"] = int(p["time"])          # all-int column
            elif i % 3 == 1 and j % 2 == 0:
                p["time"] = int(p["time"])          # mixed column
        out.append(tr)
    return out


# -- _fill_rows equivalence --------------------------------------------------


class TestFillRows:
    def _compare(self, matcher, traces, idxs, T):
        legacy = matcher._fill_rows(traces, idxs, T, cols=None)
        cols = columnar.extract_columns(traces)
        packed = matcher._fill_rows(traces, idxs, T, cols=cols)
        for a, b, name in zip(legacy[:4], packed[:4],
                              ("px", "py", "tm", "valid")):
            assert a.dtype == b.dtype, name
            assert np.array_equal(a, b), name  # bitwise, not approx
        lt, pt = legacy[4], packed[4]
        assert len(lt) == len(pt)
        for r in range(len(lt)):
            assert list(lt[r]) == list(pt[r])

    def test_bit_identical_all_rows(self, setup, matcher):
        arrays, _ = setup
        traces = _varied_traces(arrays)
        self._compare(matcher, traces, list(range(len(traces))), 16)

    def test_bit_identical_subset_and_order(self, setup, matcher):
        """Group packing indexes an arbitrary idxs subset in arbitrary
        order (bucket grouping does exactly this)."""
        arrays, _ = setup
        traces = _varied_traces(arrays)
        self._compare(matcher, traces, [5, 1, 6], 16)
        self._compare(matcher, traces, list(reversed(range(len(traces)))), 16)
        self._compare(matcher, traces, [3], 16)

    def test_zero_length_trace_packs_empty_row(self, setup, matcher):
        """The legacy loop cannot see a 0-point trace (dispatch filters
        them first); the columnar packer must still keep its row empty
        and its neighbours intact."""
        arrays, _ = setup
        traces = _varied_traces(arrays)
        traces.insert(2, {"uuid": "empty", "trace": []})
        cols = columnar.extract_columns(traces)
        px, py, tm, valid, times = matcher._fill_rows(
            traces, list(range(len(traces))), 16, cols=cols)
        assert not valid[2].any() and list(times[2]) == []
        nonempty = [i for i in range(len(traces)) if i != 2]
        ref = matcher._fill_rows(traces, nonempty, 16, cols=None)
        packed_rows = np.delete(px, 2, axis=0)
        assert np.array_equal(packed_rows, ref[0])

    def test_columns_side_channel_equivalence(self, setup, matcher):
        """A trace carrying the binary-wire "_columns" arrays must pack
        exactly like its dict-walked twin."""
        arrays, _ = setup
        traces = _varied_traces(arrays)
        with_cols = []
        for i, tr in enumerate(traces):
            tr = dict(tr)
            if i % 2:
                pts = tr["trace"]
                tr["_columns"] = {
                    "lat": np.array([p["lat"] for p in pts], np.float64),
                    "lon": np.array([p["lon"] for p in pts], np.float64),
                    "time": np.array([float(p["time"]) for p in pts],
                                     np.float64),
                }
            with_cols.append(tr)
        a = columnar.extract_columns(traces)
        b = columnar.extract_columns(with_cols)
        for name in ("lens", "lat", "lon", "time"):
            assert np.array_equal(getattr(a, name), getattr(b, name)), name
        idxs = list(range(len(traces)))
        pa = matcher._fill_rows(traces, idxs, 16, cols=a)
        pb = matcher._fill_rows(with_cols, idxs, 16, cols=b)
        for x, y in zip(pa[:4], pb[:4]):
            assert np.array_equal(x, y)


class TestPackedTimes:
    def test_quacks_like_list_of_lists(self):
        pt = columnar.PackedTimes(
            np.array([1.0, 2.0, 3.0, 10.0, 20.0], np.float64),
            np.array([3, 0, 2], np.int64), np.array([0, 3, 3], np.int64))
        assert len(pt) == 3
        assert pt[0] == [1.0, 2.0, 3.0]
        assert pt[1] == []
        assert pt[2] == [10.0, 20.0]

    def test_fill_abs_matches_row_loop(self):
        rng = np.random.default_rng(5)
        lens = np.array([4, 0, 7, 1], np.int64)
        flat = rng.uniform(1e9, 2e9, int(lens.sum()))
        offs = np.cumsum(lens) - lens
        pt = columnar.PackedTimes(flat, lens, offs)
        B, T = 4, 8
        vec = np.zeros((B, T), np.float64)
        n_vec = np.zeros(B, np.int64)
        pt.fill_abs(vec, n_vec)
        ref = np.zeros((B, T), np.float64)
        n_ref = np.zeros(B, np.int64)
        for r in range(B):
            ts = pt[r]
            ref[r, : len(ts)] = ts
            n_ref[r] = len(ts)
        assert np.array_equal(vec, ref) and np.array_equal(n_vec, n_ref)


# -- end-to-end differential: host_pack on == host_pack off ------------------


def _pair(setup, **cfg_kw):
    arrays, ubodt = setup
    on = SegmentMatcher(arrays=arrays, ubodt=ubodt,
                        config=MatcherConfig(host_pack=True, **cfg_kw))
    off = SegmentMatcher(arrays=arrays, ubodt=ubodt,
                         config=MatcherConfig(host_pack=False, **cfg_kw))
    assert on._host_pack and not off._host_pack
    return on, off


def _assert_identical(out_a, out_b):
    assert json.dumps(out_a, sort_keys=True) == json.dumps(out_b,
                                                           sort_keys=True)


class TestMatchManyDifferential:
    @pytest.mark.parametrize("kernel", ["scan", "assoc"])
    def test_kernels(self, setup, kernel):
        arrays, _ = setup
        on, off = _pair(setup, length_buckets=[16, 64],
                        viterbi_kernel=kernel)
        traces = _varied_traces(arrays) + _traces(arrays, 4, 40, seed=13)
        for tr in traces:
            tr["match_options"] = MO
        _assert_identical(on.match_many(traces), off.match_many(traces))

    def test_wide32_layout(self, setup):
        arrays, _ = setup
        ubodt32 = build_ubodt(arrays, delta=1500.0, layout="wide32")
        on = SegmentMatcher(arrays=arrays, ubodt=ubodt32,
                            config=MatcherConfig(host_pack=True,
                                                 length_buckets=[16]))
        off = SegmentMatcher(arrays=arrays, ubodt=ubodt32,
                             config=MatcherConfig(host_pack=False,
                                                  length_buckets=[16]))
        traces = _varied_traces(arrays, seed=9)
        _assert_identical(on.match_many(traces), off.match_many(traces))

    def test_sparse_model(self, setup):
        """dt=45s puts the cohort over sparse_gap_s: the sparse program
        variants must see the same packed batches either way."""
        arrays, _ = setup
        on, off = _pair(setup, length_buckets=[16], sparse=True)
        traces = _traces(arrays, 6, 12, seed=21, dt=45.0)
        _assert_identical(on.match_many(traces), off.match_many(traces))

    def test_long_trace_path(self, setup):
        """Traces beyond the top bucket take the carried-window chain
        (which packs per window, legacy either way) — the split between
        columnar bucket packing and the chain must not shift results."""
        arrays, _ = setup
        on, off = _pair(setup, length_buckets=[16])
        traces = _varied_traces(arrays) + _traces(arrays, 2, 80, seed=17)
        _assert_identical(on.match_many(traces), off.match_many(traces))

    def test_session_path(self, setup):
        from reporter_tpu.matching.session import SessionEngine, SessionStore

        arrays, _ = setup
        outs = []
        for host_pack in (True, False):
            m = SegmentMatcher(
                arrays=arrays, ubodt=setup[1],
                config=MatcherConfig(host_pack=host_pack,
                                     length_buckets=[16],
                                     session_buckets=[4, 16]))
            eng = SessionEngine(m, SessionStore(), tail_points=256)
            results = []
            for tr in _traces(arrays, 3, 12, seed=31):
                pts = tr["trace"]
                for j in range(0, len(pts), 4):
                    results.extend(eng.match_many([
                        {"uuid": tr["uuid"], "trace": pts[j:j + 4],
                         "match_options": MO}]))
            for r in results:  # wall-clock field, not part of the contract
                (r.get("_stream") or {}).get("session", {}).pop("age_s", None)
            outs.append(results)
        _assert_identical(outs[0], outs[1])


def test_env_knob_overrides_config(setup, monkeypatch):
    arrays, ubodt = setup
    monkeypatch.setenv("REPORTER_HOST_PACK", "0")
    m = SegmentMatcher(arrays=arrays, ubodt=ubodt,
                       config=MatcherConfig(length_buckets=[16]))
    assert m._host_pack is False
    monkeypatch.setenv("REPORTER_HOST_PACK", "1")
    m2 = SegmentMatcher(arrays=arrays, ubodt=ubodt,
                        config=MatcherConfig(host_pack=False,
                                             length_buckets=[16]))
    assert m2._host_pack is True
