"""Bit-identity across partitionings (docs/performance.md "One logical
matcher per pod"): the SAME batch dispatched on 1, 2, and 8 virtual
devices must produce wire- and CompactMatch-identical output — across
{scan, assoc} x {cuckoo, wide32} x sparse on/off x arena on/off,
including seam/carry chains and a mid-stream arena eviction.

The partition-rule table (parallel/rules.py) is allowed to change WHERE
bytes compute, never WHICH bytes come out: the dp axis shards
row-independent work, the gp axis resolves probes via exact psum
bit-pattern reductions, and the arena gather/scatter reconstructs the
global slab row-for-row.  Every test here is a 1-vs-N differential on
the full matcher wire output.
"""

import json

import numpy as np
import pytest

from reporter_tpu.matching import MatcherConfig, SegmentMatcher
from reporter_tpu.matching.session import SessionEngine, SessionStore
from reporter_tpu.synth import TraceSynthesizer
from reporter_tpu.tiles.arrays import build_graph_arrays
from reporter_tpu.tiles.network import grid_city
from reporter_tpu.tiles.ubodt import build_ubodt

MO = {"mode": "auto", "report_levels": [0, 1], "transition_levels": [0, 1]}
SLOT_B = 12 * 8 + 17  # one arena slot at beam_k=8


@pytest.fixture(scope="module")
def setup():
    city = grid_city(rows=5, cols=5, spacing_m=150.0)
    arrays = build_graph_arrays(city, cell_size=100.0)
    return arrays, {layout: build_ubodt(arrays, delta=1500.0, layout=layout)
                    for layout in ("cuckoo", "wide32")}


def _require_devices(n):
    import jax

    if len(jax.devices()) < n:
        pytest.skip("needs %d virtual CPU devices" % n)


def _matcher(setup, layout="cuckoo", devices=1, **kw):
    arrays, tables = setup
    cfg = MatcherConfig(length_buckets=[16], session_buckets=[4, 16],
                        ubodt_layout=layout, devices=devices, **kw)
    return SegmentMatcher(arrays=arrays, ubodt=tables[layout], config=cfg)


def _batch(arrays, n=6, pts=12, seed=3, dt=5.0, chain=True):
    synth = TraceSynthesizer(arrays, seed=seed)
    trs = [synth.synthesize(pts, dt=dt, uuid="v%d" % i, sigma=3.0,
                            max_tries=300).trace for i in range(n)]
    if chain:
        # one trace past the largest bucket: the seam/carry chain rides
        # along (dense cadence — a 40-pt route at sparse dt exceeds the
        # 5x5 grid)
        trs.append(synth.synthesize(40, dt=5.0, uuid="chain", sigma=3.0,
                                    max_tries=300).trace)
    return trs


def wire(results):
    return json.dumps(results, sort_keys=True)


def _stream_fleet(m, trs, step=2, batched=True):
    store = SessionStore()
    eng = SessionEngine(m, store, tail_points=512)
    pts_max = max(len(t["trace"]) for t in trs)
    for j in range(0, pts_max, step):
        batch = [{"uuid": t["uuid"], "trace": t["trace"][j:j + step],
                  "match_options": MO}
                 for t in trs if t["trace"][j:j + step]]
        if batched:
            eng.match_many(batch)
        else:
            for item in batch:
                eng.match_many([item])
    return store


def _assert_store_equal(a, b, uuids):
    for u in uuids:
        sa, sb = a.peek(u), b.peek(u)
        for i, what in enumerate(("edge", "offset", "break")):
            np.testing.assert_array_equal(
                np.array([r[i] for r in sa.records]),
                np.array([r[i] for r in sb.records]),
                err_msg="%s/%s" % (u, what))
    wa = {w["uuid"]: w["carry"] for w in a.export_all()}
    wb = {w["uuid"]: w["carry"] for w in b.export_all()}
    assert wa == wb  # exact f32 wire bytes


# -- dense batch + seam/carry chain: kernels x layouts x device counts -------


@pytest.fixture(scope="module")
def dense_refs(setup):
    """Single-device reference wire output per (kernel, layout), computed
    lazily so tier-1 (which runs only the scan/cuckoo cell; the rest are
    ``slow``) pays for exactly the references it compares against."""
    arrays, _ = setup
    trs = _batch(arrays)
    cache = {}

    def ref(kernel, layout):
        key = (kernel, layout)
        if key not in cache:
            m = _matcher(setup, layout=layout, viterbi_kernel=kernel)
            cache[key] = wire(m.match_many(trs))
        return cache[key]

    return trs, ref


@pytest.mark.parametrize("kernel,layout", [
    ("scan", "cuckoo"),
    pytest.param("assoc", "cuckoo", marks=pytest.mark.slow),
    pytest.param("scan", "wide32", marks=pytest.mark.slow),
    pytest.param("assoc", "wide32", marks=pytest.mark.slow),
])
def test_dense_identity_dp8(setup, dense_refs, kernel, layout):
    """8-device dp mesh == 1 device, wire-identical, both kernels x both
    layouts, seam chain included."""
    _require_devices(8)
    trs, refs = dense_refs
    m = _matcher(setup, layout=layout, viterbi_kernel=kernel, devices=8)
    assert m._mesh is not None
    assert wire(m.match_many(trs)) == refs(kernel, layout)


def test_dense_identity_dp2(setup, dense_refs):
    """The intermediate partitioning: 2 devices agree with 1 and (by
    transitivity with test_dense_identity_dp8) with 8."""
    _require_devices(2)
    trs, refs = dense_refs
    m = _matcher(setup, devices=2, viterbi_kernel="scan")
    assert wire(m.match_many(trs)) == refs("scan", "cuckoo")


def test_dense_identity_dp2_gp4(setup, dense_refs):
    """The 2-D mesh (batch x graph shards): probes resolve collectively
    over gp, output still byte-identical."""
    _require_devices(8)
    trs, refs = dense_refs
    m = _matcher(setup, devices=8, graph_devices=4, viterbi_kernel="scan")
    assert m._n_gp == 4
    assert wire(m.match_many(trs)) == refs("scan", "cuckoo")


# -- sparse on ---------------------------------------------------------------


@pytest.mark.slow  # tier-1 sparse mesh identity: test_sparse.py::test_sparse_mesh_identical
@pytest.mark.parametrize("devices", [2, 8])
def test_sparse_identity(setup, devices):
    """Sparse-cohort dispatch (>= 45 s gaps) under the mesh equals the
    single-device sparse path bit-for-bit."""
    _require_devices(devices)
    arrays, _ = setup
    trs = _batch(arrays, n=4, dt=60.0, seed=7)
    kw = dict(sparse=True, sparse_vmax_mps=16.0)
    want = wire(_matcher(setup, **kw).match_many(trs))
    m = _matcher(setup, devices=devices, **kw)
    assert m.sparse.enabled
    assert wire(m.match_many(trs)) == want


# -- arena on ----------------------------------------------------------------


@pytest.mark.parametrize("kernel", [
    "scan", pytest.param("assoc", marks=pytest.mark.slow)])
def test_arena_identity_dp8(setup, kernel):
    """Session arena sharded over 8 dp devices: streaming fleet equal to
    the 1-device host-carry reference — records and exported carry
    bytes, both kernels."""
    _require_devices(8)
    arrays, _ = setup
    trs = _batch(arrays, n=4, pts=10)
    host = _stream_fleet(_matcher(setup, viterbi_kernel=kernel), trs)
    m = _matcher(setup, viterbi_kernel=kernel, devices=8,
                 session_arena=True)
    assert m.session_arena is not None
    assert m.session_arena.hot_slots % 8 == 0  # slab splits over dp
    arena = _stream_fleet(m, trs)
    _assert_store_equal(host, arena, [t["uuid"] for t in trs])


@pytest.mark.slow
def test_arena_identity_wide32_dp8(setup):
    """The other table layout under the sharded arena."""
    _require_devices(8)
    arrays, _ = setup
    trs = _batch(arrays, n=3, pts=10, seed=5)
    host = _stream_fleet(_matcher(setup, layout="wide32"), trs)
    m = _matcher(setup, layout="wide32", devices=8, session_arena=True)
    arena = _stream_fleet(m, trs)
    _assert_store_equal(host, arena, [t["uuid"] for t in trs])


def test_arena_eviction_midstream_dp2(setup):
    """Mid-stream arena eviction UNDER the mesh: a 2-hot/2-cold slab on a
    dp-2 mesh churns (promote/evict/readback) while 6 vehicles round-
    robin — and never moves a bit vs the host-carry reference."""
    _require_devices(2)
    arrays, _ = setup
    trs = _batch(arrays, n=6, pts=10, seed=9)
    host = _stream_fleet(_matcher(setup), trs, batched=False)
    m = _matcher(setup, devices=2, session_arena=True,
                 session_arena_bytes=1 * SLOT_B,
                 session_arena_cold_bytes=2 * SLOT_B)
    s0 = m.session_arena.summary()
    assert s0["hot_slots"] == 2  # 1-slot budget rounds UP to the dp width
    arena = _stream_fleet(m, trs, batched=False)
    _assert_store_equal(host, arena, [t["uuid"] for t in trs])
    s = m.session_arena.summary()
    assert s["evictions"] > 0 and s["readbacks"] > 0


@pytest.mark.slow
def test_sparse_arena_identity_dp8(setup):
    """Sparse AND arena both on: dp-8 equals the 1-device arena twin
    bit-for-bit.  (The reference here is the 1-device ARENA path — the
    partitioning axis is what this suite isolates; the arena-vs-host
    differential itself lives in test_session_arena.py.)"""
    _require_devices(8)
    arrays, _ = setup
    trs = _batch(arrays, n=3, pts=10, seed=11, dt=60.0)
    kw = dict(sparse=True, sparse_gap_s=1.0, session_arena=True)
    one = _stream_fleet(_matcher(setup, **kw), trs)
    m = _matcher(setup, devices=8, **kw)
    arena = _stream_fleet(m, trs)
    _assert_store_equal(one, arena, [t["uuid"] for t in trs])


# -- capacity plane ----------------------------------------------------------


def test_capacity_summary_scales_with_devices(setup):
    """The /health "capacity" block: admission caps and byte budgets
    scale with the local device count (what the router's weighted
    ranking and the measurement artifact pin)."""
    _require_devices(8)
    one = _matcher(setup).capacity_summary()
    m8 = _matcher(setup, devices=8, session_arena=True,
                  session_arena_bytes=8 * SLOT_B)
    eight = m8.capacity_summary()
    assert one["devices"] == 1 and eight["devices"] == 8
    assert eight["mesh"] == {"dp": 8, "gp": 1}
    assert eight["max_device_batch"] == 8 * one["max_device_batch"]
    assert eight["max_device_points"] == 8 * one["max_device_points"]
    a = eight["session_arena"]
    assert a["devices"] == 8
    assert a["hot_bytes"] == 8 * a["hot_bytes_per_chip"]
