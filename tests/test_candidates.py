import numpy as np
import pytest

from reporter_tpu import geo
from reporter_tpu.tiles.network import grid_city
from reporter_tpu.tiles.arrays import build_graph_arrays


@pytest.fixture(scope="module")
def city():
    return grid_city(rows=5, cols=5, spacing_m=150.0)


@pytest.fixture(scope="module")
def arrays(city):
    return build_graph_arrays(city, cell_size=100.0)


def brute_force_candidates(arrays, x, y, radius):
    """Nearest point per edge within radius, via direct numpy over all segments."""
    d, t = geo.point_segment_distance_np(
        x, y, arrays.shp_ax, arrays.shp_ay, arrays.shp_bx, arrays.shp_by
    )
    best = {}
    for si in range(len(d)):
        if d[si] <= radius:
            e = int(arrays.shp_edge[si])
            off = float(arrays.shp_off[si] + t[si] * arrays.shp_len[si])
            if e not in best or d[si] < best[e][0]:
                best[e] = (float(d[si]), off)
    return best


def test_candidates_match_brute_force(arrays):
    import jax
    import jax.numpy as jnp

    from reporter_tpu.ops.candidates import find_candidates

    dg = arrays.to_device()
    fn = jax.jit(find_candidates, static_argnums=(3,))
    rng = np.random.default_rng(42)
    span_x = arrays.node_x.max() - arrays.node_x.min()
    span_y = arrays.node_y.max() - arrays.node_y.min()
    for _ in range(25):
        x = float(rng.uniform(arrays.node_x.min() - 30, arrays.node_x.min() + span_x + 30))
        y = float(rng.uniform(arrays.node_y.min() - 30, arrays.node_y.min() + span_y + 30))
        got = fn(dg, jnp.float32(x), jnp.float32(y), 16, jnp.float32(50.0))
        got_edges = {
            int(e): (float(d), float(o))
            for e, d, o in zip(np.asarray(got.edge), np.asarray(got.dist), np.asarray(got.offset))
            if e >= 0
        }
        want = brute_force_candidates(arrays, x, y, 50.0)
        if len(want) > 16:
            continue  # beam can't hold them all; skip exactness here
        assert set(got_edges) == set(want), (x, y)
        for e, (wd, wo) in want.items():
            gd, go = got_edges[e]
            assert gd == pytest.approx(wd, abs=0.5)
            assert go == pytest.approx(wo, abs=1.0)


def test_candidates_far_point_empty(arrays):
    import jax.numpy as jnp

    from reporter_tpu.ops.candidates import find_candidates

    dg = arrays.to_device()
    got = find_candidates(dg, jnp.float32(1e7), jnp.float32(1e7), 8, 50.0)
    assert (np.asarray(got.edge) == -1).all()
    assert np.isinf(np.asarray(got.dist)).all()


def test_candidates_sorted_and_deduped(arrays):
    import jax.numpy as jnp

    from reporter_tpu.ops.candidates import find_candidates

    dg = arrays.to_device()
    # a point near an intersection sees several edges
    x = float(arrays.node_x[12])
    y = float(arrays.node_y[12]) + 5.0
    # radius must respect the quadrant-sweep precondition: <= cell_size/2
    got = find_candidates(dg, jnp.float32(x), jnp.float32(y), 16, 50.0)
    edges = [int(e) for e in np.asarray(got.edge) if e >= 0]
    assert len(edges) == len(set(edges)), "duplicate edges in beam"
    d = np.asarray(got.dist)
    finite = d[np.isfinite(d)]
    assert (np.diff(finite) >= -1e-4).all(), "distances not sorted"
    assert len(edges) >= 4  # 4-way intersection, both directions nearby


def test_candidates_batch_shape(arrays):
    import jax.numpy as jnp

    from reporter_tpu.ops.candidates import find_candidates_batch

    dg = arrays.to_device()
    px = jnp.zeros((3, 7), jnp.float32)
    py = jnp.zeros((3, 7), jnp.float32)
    got = find_candidates_batch(dg, px, py, 8, 50.0)
    assert got.edge.shape == (3, 7, 8)
    assert got.dist.shape == (3, 7, 8)


def test_candidates_brute_force_at_cell_boundaries(arrays):
    """Quadrant-sweep adversarial points: exactly on and just around cell
    boundaries and half-cell lines, where the sx/sy neighbour choice flips.
    The brute-force scan is the independent completeness oracle (it shares
    no code with the quadrant rule)."""
    import jax
    import jax.numpy as jnp

    from reporter_tpu.ops.candidates import find_candidates

    dg = arrays.to_device()
    fn = jax.jit(find_candidates, static_argnums=(3,))
    cell = arrays.cell_size
    x0, y0 = arrays.grid_x0, arrays.grid_y0
    eps = [0.0, 1e-3, -1e-3, 0.49 * cell, 0.5 * cell, 0.51 * cell]
    checked = 0
    for cx in (2, 3, 4):
        for cy in (2, 3, 4):
            for ex in eps:
                for ey in (0.0, 0.5 * cell, 1e-3):
                    x = float(x0 + cx * cell + ex)
                    y = float(y0 + cy * cell + ey)
                    got = fn(dg, jnp.float32(x), jnp.float32(y), 16,
                             jnp.float32(50.0))
                    got_edges = {
                        int(e) for e in np.asarray(got.edge) if e >= 0
                    }
                    # float32 vs float64 projection can flip membership for
                    # segments within ~1 cm of the radius: require
                    # narrow(49.99) <= got <= wide(50.01)
                    want_wide = brute_force_candidates(arrays, x, y, 50.01)
                    want_narrow = brute_force_candidates(arrays, x, y, 49.99)
                    if len(want_wide) > 16:
                        continue
                    assert got_edges <= set(want_wide), (x, y)
                    assert set(want_narrow) <= got_edges, (x, y)
                    checked += 1
    assert checked > 100
