import itertools

import numpy as np
import pytest

from reporter_tpu.matching.config import MatcherConfig
from reporter_tpu.tiles.network import grid_city
from reporter_tpu.tiles.arrays import build_graph_arrays
from reporter_tpu.tiles.ubodt import build_ubodt

K = 8


@pytest.fixture(scope="module")
def city():
    return grid_city(rows=5, cols=5, spacing_m=150.0)


@pytest.fixture(scope="module")
def arrays(city):
    return build_graph_arrays(city, cell_size=100.0)


@pytest.fixture(scope="module")
def ubodt(arrays):
    return build_ubodt(arrays, delta=2000.0)


@pytest.fixture(scope="module")
def device(arrays, ubodt):
    return arrays.to_device(), ubodt.to_device()


@pytest.fixture(scope="module")
def params():
    import jax.numpy as jnp  # noqa

    from reporter_tpu.ops.viterbi import MatchParams

    return MatchParams.from_config(MatcherConfig())


def run_match(device, params, xs, ys, valid=None, times=None, kernel="scan"):
    import functools

    import jax
    import jax.numpy as jnp

    from reporter_tpu.ops.viterbi import match_trace

    dg, du = device
    xs = jnp.asarray(xs, jnp.float32)
    ys = jnp.asarray(ys, jnp.float32)
    if valid is None:
        valid = jnp.ones(xs.shape, jnp.bool_)
    else:
        valid = jnp.asarray(valid, jnp.bool_)
    if times is None:
        times = jnp.arange(xs.shape[0], dtype=jnp.float32) * 15.0
    else:
        times = jnp.asarray(times, jnp.float32)
    fn = jax.jit(functools.partial(match_trace, kernel=kernel),
                 static_argnums=(7,))
    return fn(dg, du, xs, ys, times, valid, params, K)


def street_points(arrays, row_nodes, n, jitter, rng, t_end=0.9):
    """Points along the straight line through the given node ids.  Ends
    mid-block by default: a point exactly on an intersection node ties between
    the street edge and the crossing edge (both are correct matches)."""
    xs = arrays.node_x[row_nodes]
    ys = arrays.node_y[row_nodes]
    t = np.linspace(0.05, t_end, n)
    px = np.interp(t, np.linspace(0, 1, len(xs)), xs) + rng.normal(0, jitter, n)
    py = np.interp(t, np.linspace(0, 1, len(ys)), ys) + rng.normal(0, jitter, n)
    return px, py


def test_straight_drive_matches_street(arrays, device, params):
    rng = np.random.default_rng(7)
    # middle horizontal street: nodes 10..14 (row 2 of 5x5)
    row = [2 * 5 + c for c in range(5)]
    px, py = street_points(arrays, row, 12, jitter=3.0, rng=rng)
    res = run_match(device, params, px, py)
    idx = np.asarray(res.idx)
    assert (idx >= 0).all(), "every point should match"
    edges = np.asarray(res.cand.edge)[np.arange(len(idx)), idx]
    # all matched edges must lie on that street row: both endpoints in row nodes
    for e in edges:
        assert int(arrays.edge_from[e]) in row and int(arrays.edge_to[e]) in row, e
    breaks = np.asarray(res.breaks)
    assert breaks[0] and not breaks[1:].any()


def test_viterbi_matches_exhaustive(arrays, device, params):
    import jax.numpy as jnp

    from reporter_tpu.ops.candidates import find_candidates_batch
    from reporter_tpu.ops.viterbi import transition_matrix, NEG_INF

    rng = np.random.default_rng(3)
    row = [1 * 5 + c for c in range(5)]
    px, py = street_points(arrays, row, 5, jitter=8.0, rng=rng)
    dg, du = device
    res = run_match(device, params, px, py)

    cand = find_candidates_batch(dg, jnp.asarray(px, jnp.float32), jnp.asarray(py, jnp.float32),
                                 K, params.search_radius)
    dist = np.asarray(cand.dist)
    emis = np.where(np.isfinite(dist), -0.5 * (dist / float(params.sigma_z)) ** 2, NEG_INF)
    T = len(px)
    gc = np.hypot(np.diff(px), np.diff(py))
    trans = []
    import jax

    for t in range(T - 1):
        src = jax.tree_util.tree_map(lambda a: a[t], cand)
        dst = jax.tree_util.tree_map(lambda a: a[t + 1], cand)
        logp, _ = transition_matrix(dg, du, src, dst, jnp.float32(gc[t]), jnp.float32(15.0), params)
        trans.append(np.asarray(logp))

    # exhaustive best path (no breaks expected in this easy scenario)
    best_score, best_path = -np.inf, None
    for path in itertools.product(range(K), repeat=T):
        s = emis[0, path[0]]
        for t in range(1, T):
            s += trans[t - 1][path[t - 1], path[t]] + emis[t, path[t]]
        if s > best_score:
            best_score, best_path = s, path

    idx = np.asarray(res.idx)
    got_score = emis[0, idx[0]]
    for t in range(1, T):
        got_score += trans[t - 1][idx[t - 1], idx[t]] + emis[t, idx[t]]
    assert got_score == pytest.approx(best_score, rel=1e-5)


def test_teleport_causes_break(arrays, device, params):
    rng = np.random.default_rng(11)
    row = [0 * 5 + c for c in range(5)]
    px1, py1 = street_points(arrays, row, 6, jitter=2.0, rng=rng)
    row2 = [4 * 5 + c for c in range(5)]
    px2, py2 = street_points(arrays, row2, 6, jitter=2.0, rng=rng)
    # rows 0 and 4 are 600 m apart; shrink breakage to force the break
    import dataclasses

    from reporter_tpu.ops.viterbi import MatchParams

    cfg = MatcherConfig(breakage_distance=300.0)
    p = MatchParams.from_config(cfg)
    px = np.concatenate([px1, px2])
    py = np.concatenate([py1, py2])
    res = run_match(device, p, px, py)
    breaks = np.asarray(res.breaks)
    assert breaks[6], "teleport must start a new HMM segment"
    idx = np.asarray(res.idx)
    assert (idx >= 0).all()
    edges = np.asarray(res.cand.edge)[np.arange(len(idx)), idx]
    for e in edges[:6]:
        assert int(arrays.edge_from[e]) in row
    for e in edges[6:]:
        assert int(arrays.edge_from[e]) in row2


def test_padding_equivalence(arrays, device, params):
    rng = np.random.default_rng(5)
    row = [3 * 5 + c for c in range(5)]
    px, py = street_points(arrays, row, 10, jitter=3.0, rng=rng)
    res_full = run_match(device, params, px, py)
    T_pad = 16
    px_p = np.concatenate([px, np.zeros(T_pad - len(px))])
    py_p = np.concatenate([py, np.zeros(T_pad - len(py))])
    valid = np.concatenate([np.ones(len(px), bool), np.zeros(T_pad - len(px), bool)])
    res_pad = run_match(device, params, px_p, py_p, valid)
    idx_f = np.asarray(res_full.idx)
    idx_p = np.asarray(res_pad.idx)
    assert (idx_p[len(px):] == -1).all(), "padded steps must be unmatched"
    ef = np.asarray(res_full.cand.edge)[np.arange(len(idx_f)), idx_f]
    ep = np.asarray(res_pad.cand.edge)[np.arange(len(px)), idx_p[: len(px)]]
    np.testing.assert_array_equal(ef, ep)


def test_no_candidate_gap(arrays, device, params):
    rng = np.random.default_rng(9)
    row = [2 * 5 + c for c in range(5)]
    px, py = street_points(arrays, row, 8, jitter=2.0, rng=rng)
    # move one mid point to a block centre: 75 m from every road (outside the
    # 50 m search radius but below breakage).  NB just pushing it off the
    # street is not enough -- in a grid city a crossing street is never far.
    px[4] = float(arrays.node_x[0]) + 75.0
    py[4] = float(arrays.node_y[2 * 5]) + 75.0
    res = run_match(device, params, px, py)
    idx = np.asarray(res.idx)
    assert idx[4] == -1, "point outside search radius must be unmatched"
    assert (idx[:4] >= 0).all() and (idx[5:] >= 0).all()


def _assert_kernels_agree(device, params, px, py, valid=None, times=None):
    """scan and assoc forwards must produce identical idx/breaks and
    equal finite route distances on the same trace."""
    a = run_match(device, params, px, py, valid, times, kernel="scan")
    b = run_match(device, params, px, py, valid, times, kernel="assoc")
    np.testing.assert_array_equal(np.asarray(a.idx), np.asarray(b.idx))
    np.testing.assert_array_equal(np.asarray(a.breaks), np.asarray(b.breaks))
    ra, rb = np.asarray(a.route_dist), np.asarray(b.route_dist)
    np.testing.assert_array_equal(np.isfinite(ra), np.isfinite(rb))
    fin = np.isfinite(ra)
    np.testing.assert_allclose(ra[fin], rb[fin], rtol=1e-5, atol=1e-3)


def test_assoc_matches_scan_straight_drive(arrays, device, params):
    rng = np.random.default_rng(7)
    row = [2 * 5 + c for c in range(5)]
    px, py = street_points(arrays, row, 12, jitter=3.0, rng=rng)
    _assert_kernels_agree(device, params, px, py)


def test_assoc_matches_scan_with_breaks(arrays, device, params):
    """Teleport between distant rows under a tight breakage distance: the
    assoc kernel's support recursion must place the restart at exactly the
    same step as the sequential scan."""
    from reporter_tpu.ops.viterbi import MatchParams

    rng = np.random.default_rng(11)
    px1, py1 = street_points(arrays, [0 + c for c in range(5)], 6, jitter=2.0, rng=rng)
    px2, py2 = street_points(arrays, [4 * 5 + c for c in range(5)], 6, jitter=2.0, rng=rng)
    p = MatchParams.from_config(MatcherConfig(breakage_distance=300.0))
    px = np.concatenate([px1, px2])
    py = np.concatenate([py1, py2])
    _assert_kernels_agree(device, p, px, py)
    res = run_match(device, p, px, py, kernel="assoc")
    assert np.asarray(res.breaks)[6], "assoc kernel must flag the teleport"


def test_assoc_matches_scan_padding_and_all_pad(arrays, device, params):
    rng = np.random.default_rng(5)
    row = [3 * 5 + c for c in range(5)]
    px, py = street_points(arrays, row, 10, jitter=3.0, rng=rng)
    T_pad = 16
    px_p = np.concatenate([px, np.zeros(T_pad - len(px))])
    py_p = np.concatenate([py, np.zeros(T_pad - len(py))])
    # contiguous valid prefix with a padded tail
    valid = np.concatenate([np.ones(len(px), bool), np.zeros(T_pad - len(px), bool)])
    _assert_kernels_agree(device, params, px_p, py_p, valid)
    # an all-pad row: every step frozen, every point unmatched
    none = np.zeros(T_pad, bool)
    _assert_kernels_agree(device, params, px_p, py_p, none)
    res = run_match(device, params, px_p, py_p, none, kernel="assoc")
    assert (np.asarray(res.idx) == -1).all()
    assert not np.asarray(res.breaks).any()


def test_assoc_matches_scan_backward_jitter(arrays, device, params):
    """Small backward movement within one edge (GPS jitter on a stopped
    vehicle) takes the lightly-penalised jitter transition, not a break —
    in both kernels, with the same chosen slots."""
    rng = np.random.default_rng(23)
    row = [1 * 5 + c for c in range(5)]
    px, py = street_points(arrays, row, 10, jitter=1.0, rng=rng)
    px[4] = px[3] - 3.0  # a few metres backward: jitter, not a loop route
    px[7] = px[6] - 2.0
    _assert_kernels_agree(device, params, px, py)
    res = run_match(device, params, px, py, kernel="assoc")
    assert not np.asarray(res.breaks)[1:].any()


def test_assoc_carry_chain_matches_scan(arrays, device, params):
    """Chunked long-trace streaming: both kernels must agree on every chunk
    AND carry identical seam state (same committed slots, same breaks)."""
    import functools

    import jax
    import jax.numpy as jnp

    from reporter_tpu.ops.viterbi import initial_carry_batch, match_batch_carry

    rng = np.random.default_rng(31)
    dg, du = device
    B, W, n_chunks = 2, 12, 4
    fns = {
        kern: jax.jit(functools.partial(match_batch_carry, kernel=kern),
                      static_argnums=(7,))
        for kern in ("scan", "assoc")
    }
    carries = {kern: initial_carry_batch(B, K) for kern in fns}
    row = [2 * 5 + c for c in range(5)]
    px_all, py_all = street_points(arrays, row, W * n_chunks, jitter=2.0, rng=rng)
    for c in range(n_chunks):
        px = np.tile(px_all[c * W: (c + 1) * W], (B, 1)).astype(np.float32)
        py = np.tile(py_all[c * W: (c + 1) * W], (B, 1)).astype(np.float32)
        tm = (np.arange(W) + c * W)[None, :].repeat(B, 0).astype(np.float32) * 15.0
        valid = np.ones((B, W), bool)
        valid[1, W // 2:] = False  # one row with a padded tail per chunk
        outs = {}
        for kern, fn in fns.items():
            cm, carries[kern] = fn(
                dg, du, jnp.asarray(px), jnp.asarray(py), jnp.asarray(tm),
                jnp.asarray(valid), params, K, carries[kern])
            outs[kern] = cm
        for field in ("edge", "offset", "breaks"):
            np.testing.assert_array_equal(
                np.asarray(getattr(outs["scan"], field)),
                np.asarray(getattr(outs["assoc"], field)), err_msg=field)
        np.testing.assert_array_equal(
            np.asarray(carries["scan"].committed),
            np.asarray(carries["assoc"].committed))


def test_batch_vmap_matches_single(arrays, device, params):
    import jax
    import jax.numpy as jnp

    from reporter_tpu.ops.viterbi import match_batch

    rng = np.random.default_rng(13)
    traces = []
    for r in range(3):
        row = [r * 5 + c for c in range(5)]
        traces.append(street_points(arrays, row, 9, jitter=3.0, rng=rng))
    px = jnp.asarray(np.stack([t[0] for t in traces]), jnp.float32)
    py = jnp.asarray(np.stack([t[1] for t in traces]), jnp.float32)
    valid = jnp.ones(px.shape, jnp.bool_)
    times = jnp.tile(jnp.arange(px.shape[1], dtype=jnp.float32)[None, :] * 15.0, (px.shape[0], 1))
    dg, du = device
    fn = jax.jit(match_batch, static_argnums=(7,))
    res_b = fn(dg, du, px, py, times, valid, params, K)
    for b in range(3):
        res_1 = run_match(device, params, np.asarray(px[b]), np.asarray(py[b]))
        np.testing.assert_array_equal(np.asarray(res_b.idx[b]), np.asarray(res_1.idx))
