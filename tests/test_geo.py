import math

import numpy as np
import pytest

from reporter_tpu import geo


def test_haversine_known_distance():
    # Paris -> London, ~343.5 km great circle
    d = geo.haversine_m(48.8566, 2.3522, 51.5074, -0.1278)
    assert 340_000 < d < 348_000


def test_haversine_zero():
    assert geo.haversine_m(14.5, 121.0, 14.5, 121.0) == 0.0


def test_equirectangular_close_to_haversine_at_city_scale():
    lat1, lon1 = 37.77, -122.41
    lat2, lon2 = 37.80, -122.38
    h = geo.haversine_m(lat1, lon1, lat2, lon2)
    e = geo.equirectangular_m(lat1, lon1, lat2, lon2)
    # equirectangular uses the reference's meters_per_deg constant, which sits
    # ~0.11% above the mean-radius scale haversine uses
    assert abs(h - e) / h < 2e-3


def test_local_projection_roundtrip():
    proj = geo.LocalProjection(37.77, -122.41)
    lats = np.array([37.70, 37.77, 37.84])
    lons = np.array([-122.50, -122.41, -122.32])
    x, y = proj.to_xy(lats, lons)
    lat2, lon2 = proj.to_latlon(x, y)
    np.testing.assert_allclose(lat2, lats, atol=1e-9)
    np.testing.assert_allclose(lon2, lons, atol=1e-9)


def test_local_projection_distance_agrees_with_haversine():
    proj = geo.LocalProjection(37.77, -122.41)
    x1, y1 = proj.to_xy(37.76, -122.42)
    x2, y2 = proj.to_xy(37.78, -122.40)
    d_proj = math.hypot(x2 - x1, y2 - y1)
    d_hav = geo.haversine_m(37.76, -122.42, 37.78, -122.40)
    assert abs(d_proj - d_hav) / d_hav < 2e-3


def test_point_segment_distance():
    # horizontal segment from (0,0) to (10,0)
    d, t = geo.point_segment_distance_np(5.0, 3.0, 0.0, 0.0, 10.0, 0.0)
    assert d == pytest.approx(3.0)
    assert t == pytest.approx(0.5)
    # beyond the end -> clamps
    d, t = geo.point_segment_distance_np(14.0, 3.0, 0.0, 0.0, 10.0, 0.0)
    assert d == pytest.approx(5.0)
    assert t == pytest.approx(1.0)
    # degenerate zero-length segment
    d, t = geo.point_segment_distance_np(3.0, 4.0, 0.0, 0.0, 0.0, 0.0)
    assert d == pytest.approx(5.0)
    assert t == pytest.approx(0.0)


def test_jax_haversine_matches_numpy():
    import jax.numpy as jnp

    d_np = geo.haversine_m(14.543087, 121.021019, 14.553976, 121.033997)
    d_jax = float(geo.jax_haversine_m(jnp.float32(14.543087), jnp.float32(121.021019),
                                      jnp.float32(14.553976), jnp.float32(121.033997)))
    assert abs(d_np - d_jax) < 2.0  # float32 tolerance over ~1.8 km


def test_equirectangular_matches_reference_constant():
    # Batch.java:36 meters_per_deg = 20037581.187/180
    d = geo.equirectangular_m(0.0, 0.0, 1.0, 0.0)
    assert abs(d - 20037581.187 / 180.0) < 1e-6


def test_local_projection_antimeridian():
    proj = geo.LocalProjection.for_bbox(-17.0, 179.5, -16.0, -179.5)
    # origin should sit near the antimeridian, not near lon 0
    assert abs(abs(proj.lon0) - 180.0) < 1.0
    x1, _ = proj.to_xy(-16.5, 179.9)
    x2, _ = proj.to_xy(-16.5, -179.9)
    # the two sides are ~21 km apart, contiguous across the seam
    assert abs(abs(x2 - x1) - geo.haversine_m(-16.5, 179.9, -16.5, -179.9)) < 100.0
