#!/usr/bin/env bash
# Session-matcher gating rehearsal (the CI `session-rehearsal` leg;
# runnable locally): tools/fleet.py boots 3 warmed serve replicas behind
# the session-affine router, tools/loadgen.py streams an open-loop
# PER-POINT fleet ("stream": true single-point /report bodies on
# uuid-affine sessions) against the ROUTER, and mid-stream one replica is
# SIGTERMed — a graceful drain, the lifecycle the beam handoff rides:
#
#   t+8s   replica rep-1 gets SIGTERM: it refuses new work 503
#          "draining", the router rotates its vehicles off, pulls
#          GET /sessions?export=1 and POSTs each serialised beam to the
#          replica that now inherits the uuid; the supervisor respawns
#          the drained process and the router's recovery sweep
#          rebalances the sessions back (dropping the source copies so
#          the fleet points ledger stays exact)
#
# and the verdict must hold:
#
#   1. loadgen rc 0 over the WHOLE run: availability + the per-POINT
#      stream p99 objective met, with the router's client-truth fleet
#      /debug/slo verdict agreeing (--server-slo), and the
#      stream_p99_latency objective non-vacuous and ok on the server
#   2. zero lost or duplicated session answers: every scheduled point
#      got exactly one answer, all of them 200/shed-class, and the
#      fleet-wide session ledger (router GET /sessions points_total)
#      equals the count of 200-answered points EXACTLY — every point
#      folded into exactly one live session store, across the drain,
#      the handoff and the rebalance
#   3. the handoff actually moved beams: the router's
#      reporter_router_session_handoffs_total{outcome="moved"|"rebalanced"}
#      counted > 0 and some replica imported sessions
#      (reporter_sessions_total{event="imported"} > 0 on the federated
#      scrape)
#   4. the headline: per-point p99 of the streaming path is >= 5x lower
#      than the windowed-rebatch baseline (--stream-window 8) at the
#      SAME offered point rate — the window-fill wait the session
#      matcher exists to eliminate (ISSUE 12 acceptance)
#   5. the arena leg (ISSUE 18): the whole run holds with the
#      device-resident session arena ON (REPORTER_SESSION_ARENA=1, the
#      serving default) — every /statusz shows a live session_arena
#      block, a mid-stream steady-state window shows the
#      reporter_session_arena_readbacks_total counter FLAT (a packed
#      step performs zero per-step host readbacks; the counter may grow
#      only on checkpoint/drain/export), and after the drain + rebalance
#      the surviving replicas' counters HAVE grown (the handoff's
#      pop/export reads are exactly the reads the counter exists for)
#
# Usage: tests/session_rehearsal.sh [workdir]
set -euo pipefail

# shared spawn/trap/cleanup/wait helpers (tests/rehearsal_lib.sh)
. "$(dirname "$0")/rehearsal_lib.sh"
export REPORTER_RETRY_BASE_S="${REPORTER_RETRY_BASE_S:-0.05}"
# snappy probing: the drain window is short, the handoff rides the probe
export REPORTER_ROUTER_PROBE_S="${REPORTER_ROUTER_PROBE_S:-0.25}"
# the drained replica lingers after idle so the router can pull its
# sessions before the listener closes (docs/serving-fleet.md)
export REPORTER_DRAIN_LINGER_S="${REPORTER_DRAIN_LINGER_S:-2.0}"
# the serving objectives BOTH sides state (loadgen --server-slo compares
# like with like); the stream objective is the per-point gate
export REPORTER_SLO_AVAILABILITY=0.95
export REPORTER_SLO_P99_MS=8000
export REPORTER_SLO_P999_MS=0
export REPORTER_SLO_DEGRADED_FRAC=0
export REPORTER_SLO_STREAM_P99_MS=2500
# the arena leg: carried beams device-resident (the serving default —
# pinned explicitly so this gate keeps meaning it even if the default
# moves); the whole drain/handoff/ledger arc below runs with slot-handle
# sessions and must not move a bit
export REPORTER_SESSION_ARENA=1
reh_init "${1:-}" reporter-session
export REPORTER_XLA_CACHE_DIR="$WORK/xla-cache"
ROUTER_PORT=18081
BASE_PORT=18082
echo "session rehearsal workdir: $WORK"

cat > "$WORK/config.json" <<EOF
{
  "network": {"type": "grid", "rows": 8, "cols": 8, "spacing_m": 200},
  "matcher": {"sigma_z": 4.07, "beta": 3.0, "search_radius": 50.0,
              "length_buckets": [16],
              "session_buckets": [4, 16],
              "session_tail_points": 64,
              "warmup_batch_sizes": [1, 4, 16]},
  "backend": "jax",
  "batch": {"max_batch": 64, "max_wait_ms": 5, "session_wait_ms": 2}
}
EOF

# ---- boot the fleet -------------------------------------------------------
python tools/fleet.py --config "$WORK/config.json" --replicas 3 \
    --base-port "$BASE_PORT" --router-port "$ROUTER_PORT" \
    --workdir "$WORK" --warmup --cpu-default --drain-grace 20 \
    > "$WORK/fleet.log" 2>&1 &
FLEET_PID=$!
reh_track_fleet "$FLEET_PID" "$WORK"

if ! reh_wait_fleet "http://127.0.0.1:$ROUTER_PORT" 3 "$BASE_PORT" 3 600 warmed; then
    echo "FAIL: fleet never reached 3 warmed replicas; fleet log tail:"
    tail -30 "$WORK/fleet.log"
    for f in "$WORK"/replica-*.log "$WORK"/router.log; do
        echo "--- $f"; tail -10 "$f" 2>/dev/null || true
    done
    exit 1
fi
echo "fleet up: 3 warmed replicas behind the router"

# every replica serves with a live arena: /statusz session_arena non-null
python - "$BASE_PORT" <<'EOF'
import json, sys, urllib.request

base = int(sys.argv[1])
for i in range(3):
    with urllib.request.urlopen(
            "http://127.0.0.1:%d/statusz" % (base + i), timeout=15) as f:
        st = json.loads(f.read().decode())
    a = st.get("session_arena")
    assert a is not None, "replica %d serves without a session arena" % i
    assert a["hot_slots"] >= 1 and a["slot_bytes"] > 0, a
print("session arena live on all 3 replicas (hot_slots=%d, slot_bytes=%d)"
      % (a["hot_slots"], a["slot_bytes"]))
EOF

# ---- phase 1: the windowed-rebatch BASELINE at the same point rate --------
# (short, chaos-free: the number the streaming path is judged against)
python tools/loadgen.py --url "http://127.0.0.1:$ROUTER_PORT" \
    --stream --stream-window 8 \
    --rate 25 --duration 12 --vehicles 24 --points 64 --window 16 --grid 8 \
    --seed 7 --concurrency 32 --timeout-s 8 \
    --slo-availability 0.95 --slo-p99-ms 120000 \
    --out "$WORK/loadgen_windowed.json"
echo "windowed-rebatch baseline captured"

# ---- phase 2: per-point streaming, SIGTERM drain mid-stream ---------------
python tools/loadgen.py --url "http://127.0.0.1:$ROUTER_PORT" \
    --stream \
    --rate 25 --duration 30 --vehicles 24 --points 64 --window 16 --grid 8 \
    --seed 11 --concurrency 32 --timeout-s 8 \
    --slo-availability 0.95 --slo-p99-ms 8000 --server-slo \
    --dump-samples "$WORK/stream_samples.jsonl" \
    --out "$WORK/loadgen_stream.json" &
LOADGEN_PID=$!

# steady-state transfer-counter window: two scrapes of every replica's
# reporter_session_arena_readbacks_total mid-stream, BEFORE any drain or
# export — the delta must be ZERO (a packed session step moves no beam
# bytes host-side; only checkpoint/drain/export may grow the counter)
_scrape_readbacks() {
    python - "$BASE_PORT" <<'EOF'
import sys, urllib.request

sys.path.insert(0, ".")
from reporter_tpu.obs.quantile import parse_metrics

base = int(sys.argv[1])
tot = 0
for i in range(3):
    with urllib.request.urlopen(
            "http://127.0.0.1:%d/metrics" % (base + i), timeout=15) as f:
        m = parse_metrics(f.read().decode())
    for _lv, v in m.get("reporter_session_arena_readbacks_total",
                        {}).items():
        tot += int(v)
print(tot)
EOF
}
sleep 3
RB_STEADY_0=$(_scrape_readbacks)
sleep 4
RB_STEADY_1=$(_scrape_readbacks)
if [ "$RB_STEADY_0" != "$RB_STEADY_1" ]; then
    echo "FAIL: arena readbacks grew $RB_STEADY_0 -> $RB_STEADY_1 during"
    echo "      steady-state streaming — a per-step host transfer leaked"
    exit 1
fi
echo "steady-state transfer counter flat: $RB_STEADY_0 readbacks across" \
     "both mid-stream scrapes (zero per-step host readbacks)"

sleep 1
VICTIM_PID=$(python -c "
import json; s = json.load(open('$WORK/fleet.json'))
print(s['replicas'][1]['pid'])")
DRAIN_EPOCH=$(python -c "import time; print(time.time())")
kill -TERM "$VICTIM_PID"
echo "SIGTERMed replica rep-1 (pid $VICTIM_PID) at $DRAIN_EPOCH — graceful drain + beam handoff"

set +e
wait "$LOADGEN_PID"
LOADGEN_RC=$?
set -e
if [ "$LOADGEN_RC" != 0 ]; then
    echo "FAIL: loadgen rc $LOADGEN_RC — the streaming SLO did not survive"
    echo "      a graceful drain (artifact: loadgen_stream.json)"
    python -c "
import json; a = json.load(open('$WORK/loadgen_stream.json'))
print(json.dumps({k: a[k] for k in ('status', 'quantiles', 'slo')}, indent=1))" \
        2>/dev/null || true
    tail -20 "$WORK/router.log"
    exit 1
fi
echo "loadgen streaming SLO verdict: PASS (rc 0) across the drain"

# let the recovery rebalance + source drops settle before reading ledgers
sleep 3

# ---- assertions -----------------------------------------------------------
python - "$WORK" "http://127.0.0.1:$ROUTER_PORT" "$DRAIN_EPOCH" <<'EOF'
import json, sys, urllib.request

work, router, drain_epoch = sys.argv[1], sys.argv[2], float(sys.argv[3])
sys.path.insert(0, ".")
from reporter_tpu.obs.quantile import parse_metrics

def get(url):
    with urllib.request.urlopen(url, timeout=15) as f:
        return json.loads(f.read().decode())

art = json.load(open(work + "/loadgen_stream.json"))
base = json.load(open(work + "/loadgen_windowed.json"))
rows = [json.loads(l) for l in open(work + "/stream_samples.jsonl")]

# 1b. the per-point stream objective on the SERVER side: non-vacuous, ok
server = art["slo"]["server"]
assert server and server.get("ok") is True, "router fleet verdict not ok"
obj = next((o for o in server.get("objectives", ())
            if o.get("name") == "stream_p99_latency"), None)
assert obj is not None, "stream_p99_latency objective missing on the router"
assert obj.get("value") is not None, "stream objective vacuous (no traffic)"
assert obj.get("ok") is True, obj
print("per-point fleet SLO: stream p99 %.1f ms <= %.0f ms target"
      % (obj["value"] * 1000.0, obj["target"] * 1000.0))

# 2. zero lost / duplicated answers, and the exact fleet points ledger
assert len(rows) == art["requests"], "sample rows != scheduled points"
allowed = {200, 429, 503}
bad = [r for r in rows if r["code"] not in allowed]
assert not bad, "non-shed client errors: %r" % bad[:5]
n200 = sum(1 for r in rows if r["code"] == 200)
assert n200 >= 0.95 * len(rows), (n200, len(rows))
fleet = get(router + "/sessions")
assert fleet["points_total"] == n200, (
    "session points ledger %d != %d answered points — a point was lost "
    "or duplicated across the drain/handoff (%r)"
    % (fleet["points_total"], n200, fleet["replicas"]))
print("ledger exact: %d answered points == %d points in %d live sessions "
      "across %s" % (n200, fleet["points_total"], fleet["sessions"],
                     sorted(fleet["replicas"])))

# 3. the handoff moved beams (drain export -> import, or the recovery
# rebalance) and a replica imported them
with urllib.request.urlopen(router + "/metrics?pull=1", timeout=15) as f:
    m = parse_metrics(f.read().decode())
ho = {dict(lv).get("outcome"): v
      for lv, v in m.get("reporter_router_session_handoffs_total",
                         {}).items()}
moved = int(ho.get("moved", 0)) + int(ho.get("rebalanced", 0))
assert moved > 0, "no session beams moved across the drain: %r" % ho
imported = sum(
    v for lv, v in m.get("reporter_sessions_total", {}).items()
    if dict(lv).get("event") == "imported" and "replica" in dict(lv))
assert imported > 0, "no replica imported handed-off sessions"
assert int(ho.get("import_failed", 0)) == 0, ho
print("beam handoff: %d moved/rebalanced (%r), %d imported replica-side"
      % (moved, ho, imported))

# 4. the headline: streaming per-point p99 >= 5x lower than the
# windowed-rebatch baseline at the same offered point rate
sp99 = art["quantiles"]["p99_ms"]
wp99 = base["quantiles"]["p99_ms"]
assert sp99 and wp99, (sp99, wp99)
ratio = wp99 / sp99
assert ratio >= 5.0, (
    "streaming per-point p99 %.1f ms vs windowed-rebatch %.1f ms: "
    "only %.1fx (< 5x acceptance)" % (sp99, wp99, ratio))
print("per-point p99: stream %.1f ms vs windowed-rebatch %.1f ms "
      "(%.1fx lower; >= 5x required)" % (sp99, wp99, ratio))
EOF

# ...and the counter DOES grow on export — the only sanctioned readback.
# (The drain's own export readbacks died with the drained process, and
# the recovery rebalance may still be waiting on the respawn's warmup,
# so drive the seam explicitly: a wire export on every live replica must
# read each resident beam off the device exactly where the streaming
# steps read nothing.)
RB_BEFORE_EXPORT=$(_scrape_readbacks)
for i in 0 1 2; do
    curl -sf "http://127.0.0.1:$((BASE_PORT + i))/sessions?export=1" \
        > /dev/null || true
done
RB_AFTER_EXPORT=$(_scrape_readbacks)
if [ "$RB_AFTER_EXPORT" -le "$RB_BEFORE_EXPORT" ]; then
    echo "FAIL: arena readbacks $RB_BEFORE_EXPORT -> $RB_AFTER_EXPORT"
    echo "      across a fleet-wide wire export — the export did not read"
    echo "      the resident beams off device (are sessions resident?)"
    exit 1
fi
echo "arena readbacks grow only on export: $RB_BEFORE_EXPORT ->" \
     "$RB_AFTER_EXPORT across an explicit fleet-wide wire export" \
     "(steady-state window above stayed flat)"

# ---- graceful fleet drain: exit 0, nothing stranded -----------------------
reh_stop_fleet
echo "session rehearsal OK (artifacts in $WORK)"
