"""Native (C++) batched association vs the pure-Python oracle.

The two implementations must produce byte-identical wire records: the C++
mirrors segments.py's double arithmetic operation-for-operation and the
wrapper applies the same rounding.
"""

import numpy as np
import pytest

from reporter_tpu.matching import MatcherConfig, SegmentMatcher
from reporter_tpu.matching.assoc_native import (
    _fallback,
    associate_segments_batch,
)
from reporter_tpu.native import get_lib
from reporter_tpu.synth import TraceSynthesizer
from reporter_tpu.tiles.arrays import build_graph_arrays
from reporter_tpu.tiles.network import grid_city
from reporter_tpu.tiles.ubodt import build_ubodt


@pytest.fixture(scope="module")
def setup():
    city = grid_city(rows=6, cols=6, spacing_m=150.0)
    arrays = build_graph_arrays(city, cell_size=100.0)
    ubodt = build_ubodt(arrays, delta=1200.0)
    return arrays, ubodt


def _matched_batch(arrays, ubodt, B=8, T=24, seed=3):
    cfg = MatcherConfig()
    m = SegmentMatcher(arrays=arrays, ubodt=ubodt, config=cfg, backend="cpu")
    synth = TraceSynthesizer(arrays, seed=seed)
    straces = synth.batch(B, T, dt=5.0, sigma=6.0)
    px = np.zeros((B, T), np.float32)
    py = np.zeros((B, T), np.float32)
    tm = np.zeros((B, T), np.float32)
    abs_tm = np.zeros((B, T), np.float64)
    valid = np.ones((B, T), bool)
    for i, s in enumerate(straces):
        pts = s.trace["trace"]
        x, y = arrays.proj.to_xy([p["lat"] for p in pts], [p["lon"] for p in pts])
        px[i], py[i] = x, y
        ts = np.asarray([p["time"] for p in pts], np.float64)
        tm[i] = ts - ts[0]
        abs_tm[i] = ts
    edge, offset, breaks = m._run_batch(px, py, tm, valid)
    return cfg, edge, offset, breaks, abs_tm


def test_native_matches_python_oracle(setup):
    arrays, ubodt = setup
    lib = get_lib()
    if lib is None:
        pytest.skip("no native compiler available")
    cfg, edge, offset, breaks, abs_tm = _matched_batch(arrays, ubodt)
    B, T = edge.shape
    # exercise flush paths: unmatched points and forced mid-trace breaks
    edge = edge.copy()
    breaks = breaks.copy()
    edge[1, 7] = -1
    edge[2, 3:6] = -1
    breaks[3, 10] = True
    n_pts = np.full(B, T, np.int32)
    n_pts[4] = 9  # short row: padded tail must be ignored

    kw = dict(
        queue_thresh_mps=cfg.queue_speed_threshold_kph / 3.6,
        back_tol=2.0 * cfg.sigma_z + 5.0,
    )
    native = associate_segments_batch(
        arrays, ubodt, edge, offset, breaks, abs_tm, n_pts, lib=lib, **kw
    )
    oracle = _fallback(
        arrays, ubodt, edge, offset, breaks, abs_tm, n_pts,
        kw["queue_thresh_mps"], kw["back_tol"],
    )
    assert native == oracle


def test_native_all_unmatched(setup):
    arrays, ubodt = setup
    lib = get_lib()
    if lib is None:
        pytest.skip("no native compiler available")
    B, T = 3, 8
    edge = np.full((B, T), -1, np.int32)
    offset = np.zeros((B, T), np.float32)
    breaks = np.zeros((B, T), bool)
    tm = np.arange(T, dtype=np.float64)[None, :].repeat(B, 0)
    out = associate_segments_batch(
        arrays, ubodt, edge, offset, breaks, tm, np.full(B, T, np.int32), lib=lib
    )
    assert out == [[], [], []]


def test_native_mt_matches_single_thread(setup, monkeypatch):
    """The multithreaded entry must produce byte-identical records for every
    thread count, including uneven row partitions (B not divisible)."""
    arrays, ubodt = setup
    lib = get_lib()
    if lib is None or not hasattr(lib, "rn_associate_batch_mt"):
        pytest.skip("native mt entry unavailable")
    cfg, edge, offset, breaks, abs_tm = _matched_batch(arrays, ubodt, B=13, T=24)
    B, T = edge.shape
    edge = edge.copy()
    edge[0, 5] = -1  # flush paths in the first and last thread's ranges
    edge[12, 20] = -1
    n_pts = np.full(B, T, np.int32)
    n_pts[6] = 11
    kw = dict(
        queue_thresh_mps=cfg.queue_speed_threshold_kph / 3.6,
        back_tol=2.0 * cfg.sigma_z + 5.0,
    )
    outs = []
    for threads in ("1", "3", "8", "32"):  # 32 > B exercises the B clamp
        monkeypatch.setenv("REPORTER_ASSOC_THREADS", threads)
        outs.append(
            associate_segments_batch(
                arrays, ubodt, edge, offset, breaks, abs_tm, n_pts, lib=lib, **kw
            )
        )
    oracle = _fallback(
        arrays, ubodt, edge, offset, breaks, abs_tm, n_pts,
        kw["queue_thresh_mps"], kw["back_tol"],
    )
    for out in outs:
        assert out == oracle


def test_records_extension_matches_python_loop(setup, monkeypatch):
    """The CPython record materialiser (native/records_ext.c) must be
    byte-identical to the pure-Python column loop it replaces: same key
    order, same builtins.round results, same -1 sentinels."""
    from reporter_tpu.matching import assoc_native as an
    from reporter_tpu import native as rn

    lib = get_lib()
    if lib is None or rn.get_records_ext() is None:
        pytest.skip("no native compiler available")
    arrays, ubodt = setup
    cfg, edge, offset, breaks, abs_tm = _matched_batch(arrays, ubodt)
    B, T = edge.shape
    n_pts = np.full(B, T, np.int32)
    kw = dict(
        queue_thresh_mps=cfg.queue_speed_threshold_kph / 3.6,
        back_tol=2.0 * cfg.sigma_z + 5.0,
    )
    fast = associate_segments_batch(
        arrays, ubodt, edge, offset, breaks, abs_tm, n_pts, lib=lib, **kw)
    monkeypatch.setattr(an, "get_records_ext", lambda: None)
    slow = associate_segments_batch(
        arrays, ubodt, edge, offset, breaks, abs_tm, n_pts, lib=lib, **kw)
    assert fast == slow
    import json

    assert json.dumps(fast) == json.dumps(slow)
