"""Spec-derived Valhalla .gph codec (tiles/gph.py): synthetic round-trip
fixtures — encode_tiles -> decode_gph -> network_from_tiles must
reproduce the source network up to the 1e-6-degree coordinate
quantisation the baldr fixed-point layout imposes, and a decoded network
must drive the matcher exactly like the original (closing the VERDICT
".gph decoder" partial within the documented no-sample-tiles boundary)."""

import json

import numpy as np
import pytest

from reporter_tpu.matching import MatcherConfig, SegmentMatcher
from reporter_tpu.tiles.arrays import build_graph_arrays
from reporter_tpu.tiles.gph import (
    GPH_VERSION, GphError, decode_gph, decode_shape, encode_shape,
    encode_tiles, network_from_tiles, pack_graphid, unpack_graphid,
)
from reporter_tpu.tiles.network import Edge, RoadNetwork, grid_city


def q6(v: float) -> float:
    return round(v * 1e6) / 1e6


class TestShapeCodec:
    def test_round_trip_property(self):
        rng = np.random.default_rng(5)
        for _ in range(50):
            n = int(rng.integers(1, 30))
            pts = [(float(rng.uniform(-85, 85)),
                    float(rng.uniform(-179.9, 179.9))) for _ in range(n)]
            got = decode_shape(encode_shape(pts))
            assert got == [(q6(a), q6(b)) for a, b in pts]

    def test_torn_varint_raises(self):
        data = encode_shape([(52.5, 13.4)])
        with pytest.raises(GphError):
            decode_shape(data[:-1] + bytes([data[-1] | 0x80]))


class TestGraphId:
    def test_round_trip_and_bounds(self):
        assert unpack_graphid(pack_graphid(2, 415760, 91)) == (2, 415760, 91)
        with pytest.raises(GphError):
            pack_graphid(8, 0, 0)
        with pytest.raises(GphError):
            pack_graphid(0, 1 << 22, 0)


class TestTileRoundTrip:
    def test_fields_survive(self):
        city = grid_city(rows=4, cols=4, spacing_m=200.0)
        for i, e in enumerate(city.edges):
            e.way_id = 1000 + i
        tiles = encode_tiles(city)
        assert all(path.endswith(".gph") for path in tiles)
        decoded = [decode_gph(b) for b in tiles.values()]
        assert all(t.version == GPH_VERSION for t in decoded)
        net = network_from_tiles(decoded)
        assert net.num_nodes == city.num_nodes
        assert net.num_edges == city.num_edges
        assert np.allclose(net.node_lat, city.node_lat, atol=1.1e-6)
        assert np.allclose(net.node_lon, city.node_lon, atol=1.1e-6)
        # per-edge fields survive (edges regroup by from-node; compare as
        # multisets keyed on endpoints)
        def eset(n):
            return sorted((e.from_node, e.to_node, round(e.speed_kph),
                           e.way_id) for e in n.edges)
        assert eset(net) == eset(city)

    def test_cross_tile_references(self):
        """Nodes spanning a 0.25-degree tile boundary decode back into
        one connected network (end nodes are cross-tile GraphIds)."""
        net = RoadNetwork()
        a = net.add_node(0.2499, 13.0)   # tile south of the boundary
        b = net.add_node(0.2501, 13.0)   # tile north of it
        net.add_edge(Edge(a, b, speed_kph=30.0, way_id=7))
        net.add_edge(Edge(b, a, speed_kph=30.0, way_id=7))
        tiles = encode_tiles(net)
        assert len(tiles) == 2
        back = network_from_tiles(tiles.values())
        assert back.num_nodes == 2 and back.num_edges == 2
        assert {(e.from_node, e.to_node) for e in back.edges} == \
            {(0, 1), (1, 0)}
        # a tile set missing the referenced neighbour fails loudly
        with pytest.raises(GphError):
            network_from_tiles([next(iter(tiles.values()))])

    def test_malformed_streams_raise(self):
        city = grid_city(rows=3, cols=3, spacing_m=200.0)
        data = next(iter(encode_tiles(city).values()))
        with pytest.raises(GphError):
            decode_gph(data[:100])          # truncated header
        with pytest.raises(GphError):
            decode_gph(data[:300])          # truncated sections
        bad = bytearray(data)
        bad[8:24] = b"9.9.9".ljust(16, b"\x00")
        with pytest.raises(GphError):
            decode_gph(bytes(bad))          # major-version mismatch


class TestMatcherParity:
    def test_decoded_network_matches_identically(self):
        """The matcher over the decoded network produces the same wire
        output as over a network built from the SAME quantised
        coordinates — the decoder is transparent to everything
        downstream."""
        city = grid_city(rows=4, cols=4, spacing_m=200.0)
        net = network_from_tiles(encode_tiles(city).values())
        # quantise the original the way the fixed-point layout does AND
        # regroup edges by from-node the way the NodeInfo adjacency
        # window does, so the comparison isolates the byte codec (not
        # the 1e-6 rounding or the edge-id renumbering)
        qcity = RoadNetwork()
        for lat, lon in zip(city.node_lat, city.node_lon):
            qcity.add_node(q6(lat), q6(lon))
        per_node = {}
        for e in city.edges:
            per_node.setdefault(e.from_node, []).append(e)
        for i in range(city.num_nodes):
            for e in per_node.get(i, ()):
                qcity.add_edge(Edge(e.from_node, e.to_node,
                                    speed_kph=float(round(e.speed_kph)),
                                    internal=e.internal,
                                    way_id=e.way_id))
        cfg = MatcherConfig(length_buckets=[16])
        outs = []
        for n in (qcity, net):
            arrays = build_graph_arrays(n, cell_size=100.0)
            m = SegmentMatcher(arrays=arrays, config=cfg)
            xs = np.linspace(arrays.node_x[4], arrays.node_x[7], 9)
            ys = np.linspace(arrays.node_y[4], arrays.node_y[7], 9) + 3.0
            lat, lon = arrays.proj.to_latlon(xs, ys)
            outs.append(m.match_many([{"uuid": "v", "trace": [
                {"lat": float(a), "lon": float(o), "time": 1000.0 + 15 * i}
                for i, (a, o) in enumerate(zip(lat, lon))]}]))
        # edge ids may renumber (edges regroup by from-node), so compare
        # the wire segments, which speak OSMLR/segment terms
        assert json.dumps(outs[0], sort_keys=True) == \
            json.dumps(outs[1], sort_keys=True)
