import heapq

import numpy as np
import pytest

from reporter_tpu.tiles.network import grid_city
from reporter_tpu.tiles.arrays import build_graph_arrays
from reporter_tpu.tiles.ubodt import build_ubodt, pair_hash


@pytest.fixture(scope="module")
def city():
    return grid_city(rows=5, cols=5, spacing_m=150.0)


@pytest.fixture(scope="module")
def arrays(city):
    return build_graph_arrays(city, cell_size=100.0)


@pytest.fixture(scope="module")
def ubodt(arrays):
    return build_ubodt(arrays, delta=1000.0)


def reference_dijkstra(arrays, src):
    """Independent textbook Dijkstra over all nodes (no bound)."""
    dist = {src: 0.0}
    heap = [(0.0, src)]
    done = {}
    while heap:
        d, n = heapq.heappop(heap)
        if n in done:
            continue
        done[n] = d
        for k in range(arrays.out_start[n], arrays.out_start[n + 1]):
            e = int(arrays.out_edges[k])
            m = int(arrays.edge_to[e])
            nd = d + float(arrays.edge_len[e])
            if nd < dist.get(m, float("inf")):
                dist[m] = nd
                heapq.heappush(heap, (nd, m))
    return done


def test_ubodt_distances_match_dijkstra(arrays, ubodt):
    for src in range(0, arrays.num_nodes, 7):
        ref = reference_dijkstra(arrays, src)
        for dst, d in ref.items():
            got, _ = ubodt.lookup(src, dst)
            if d <= 1000.0:
                assert got == pytest.approx(d, rel=1e-5), (src, dst)
            else:
                assert got == float("inf")


def test_ubodt_self_distance(arrays, ubodt):
    for n in range(arrays.num_nodes):
        d, fe = ubodt.lookup(n, n)
        assert d == 0.0 and fe == -1


def test_ubodt_miss(ubodt):
    assert ubodt.lookup(0, 10_000)[0] == float("inf")


def test_path_reconstruction(arrays, ubodt):
    for src in range(0, arrays.num_nodes, 5):
        ref = reference_dijkstra(arrays, src)
        for dst, d in ref.items():
            if d > 1000.0 or dst == src:
                continue
            path = ubodt.path_edges(src, dst)
            assert path is not None, (src, dst)
            # path must be connected, start at src, end at dst, and sum to d
            assert int(arrays.edge_from[path[0]]) == src
            assert int(arrays.edge_to[path[-1]]) == dst
            for a, b in zip(path, path[1:]):
                assert int(arrays.edge_to[a]) == int(arrays.edge_from[b])
            total = sum(float(arrays.edge_len[e]) for e in path)
            assert total == pytest.approx(d, rel=1e-5)


def test_native_builder_bit_identical(arrays):
    """The C++ builder (rn_ubodt_build + rn_cuckoo_pack) must produce the
    exact table the Python oracle does: same rows in the same order, same
    deterministic cuckoo placement -- byte-for-byte equal arrays."""
    from reporter_tpu.native import get_lib

    if get_lib() is None:
        pytest.skip("native library unavailable")
    u_py = build_ubodt(arrays, delta=1000.0, use_native=False)
    u_nat = build_ubodt(arrays, delta=1000.0, use_native=True)
    assert u_nat.num_rows == u_py.num_rows
    assert u_nat.bmask == u_py.bmask
    assert u_nat.max_kicks == u_py.max_kicks
    np.testing.assert_array_equal(u_nat.packed, u_py.packed)


def test_native_builder_threaded_deterministic(arrays):
    """Dynamic chunk scheduling must not change row order: 1-thread and
    N-thread builds are identical."""
    from reporter_tpu.tiles.ubodt import _native_build_rows

    one = _native_build_rows(arrays, 1000.0, 1)
    if one is None:
        pytest.skip("native library unavailable")
    many = _native_build_rows(arrays, 1000.0, 8)
    for a, b in zip(one, many):
        np.testing.assert_array_equal(a, b)


def test_device_lookup_matches_host(arrays, ubodt):
    import jax.numpy as jnp

    from reporter_tpu.ops.hashtable import ubodt_lookup, device_pair_hash

    du = ubodt.to_device()
    rng = np.random.default_rng(0)
    src = rng.integers(0, arrays.num_nodes, size=200).astype(np.int32)
    dst = rng.integers(0, arrays.num_nodes, size=200).astype(np.int32)
    d_dev, t_dev, fe_dev = ubodt_lookup(du, jnp.asarray(src), jnp.asarray(dst))
    d_dev = np.asarray(d_dev)
    fe_dev = np.asarray(fe_dev)
    for i in range(len(src)):
        d_host, fe_host = ubodt.lookup(int(src[i]), int(dst[i]))
        if np.isinf(d_host):
            assert np.isinf(d_dev[i])
        else:
            assert d_dev[i] == pytest.approx(d_host, rel=1e-6)
            assert fe_dev[i] == fe_host

    # hash parity host vs device (both bucket choices)
    from reporter_tpu.ops.hashtable import device_pair_hash2
    from reporter_tpu.tiles.ubodt import pair_hash2

    mask = ubodt.bmask
    h_host = np.array([int(pair_hash(np.int64(s), np.int64(t), mask)) for s, t in zip(src, dst)])
    h_dev = np.asarray(device_pair_hash(jnp.asarray(src), jnp.asarray(dst), mask))
    np.testing.assert_array_equal(h_host, h_dev)
    h2_host = np.array([int(pair_hash2(np.int64(s), np.int64(t), mask)) for s, t in zip(src, dst)])
    h2_dev = np.asarray(device_pair_hash2(jnp.asarray(src), jnp.asarray(dst), mask))
    np.testing.assert_array_equal(h2_host, h2_dev)


def test_cuckoo_pack_high_load_bit_identical():
    """Displacement-heavy regime: unique random keys packed at ~0.8 load
    must still resolve every lookup, and the C++/Python packers must stay
    bit-identical through the eviction walks."""
    from reporter_tpu.native import get_lib
    from reporter_tpu.tiles.ubodt import ubodt_from_columns

    rng = np.random.default_rng(42)
    n = 26000
    keys = rng.choice(10_000_000, size=(n, 2), replace=False)
    src = keys[:, 0].astype(np.int32)
    dst = keys[:, 1].astype(np.int32)
    dist = rng.random(n).astype(np.float32) * 1000
    tm = rng.random(n).astype(np.float32) * 100
    fe = rng.integers(0, 1 << 20, n).astype(np.int32)

    u_py = ubodt_from_columns(src, dst, dist, tm, fe, delta=1000.0,
                              load_factor=0.8, use_native=False)
    assert u_py.num_rows == n
    # every key resolves to its row
    for i in range(0, n, 997):
        d, t, f = u_py.lookup_full(int(src[i]), int(dst[i]))
        assert d == pytest.approx(float(dist[i]), rel=1e-6)
        assert f == int(fe[i])
    assert u_py.lookup(1, 2)[0] == float("inf")  # a miss stays a miss
    assert u_py.max_kicks > 0, "high-load pack never displaced: not a stress test"

    if get_lib() is not None:
        u_nat = ubodt_from_columns(src, dst, dist, tm, fe, delta=1000.0,
                                   load_factor=0.8, use_native=True)
        assert u_nat.bmask == u_py.bmask
        assert u_nat.max_kicks == u_py.max_kicks
        np.testing.assert_array_equal(u_nat.packed, u_py.packed)


# -- wide32 layout (single-hash 32-entry buckets) ----------------------------


def _random_columns(rng, n):
    keys = rng.choice(10_000_000, size=(n, 2), replace=False)
    return (keys[:, 0].astype(np.int32), keys[:, 1].astype(np.int32),
            (rng.random(n) * 1000).astype(np.float32),
            (rng.random(n) * 100).astype(np.float32),
            rng.integers(0, 1 << 20, n).astype(np.int32))


@pytest.mark.parametrize("seed,n", [(1, 500), (2, 26000), (3, 0)])
def test_wide_pack_python_native_bit_identical(seed, n):
    """The C++ wide packer (rn_wide_pack) and the Python twin must produce
    byte-identical tables on random key columns, including the empty
    table."""
    from reporter_tpu.native import get_lib
    from reporter_tpu.tiles.ubodt import ubodt_from_columns

    if get_lib() is None:
        pytest.skip("native library unavailable")
    rng = np.random.default_rng(seed)
    src, dst, dist, tm, fe = _random_columns(rng, n)
    u_py = ubodt_from_columns(src, dst, dist, tm, fe, delta=1000.0,
                              layout="wide32", use_native=False)
    u_nat = ubodt_from_columns(src, dst, dist, tm, fe, delta=1000.0,
                               layout="wide32", use_native=True)
    assert u_py.layout == u_nat.layout == "wide32"
    assert u_py.max_probes == 1
    assert u_nat.bmask == u_py.bmask
    np.testing.assert_array_equal(u_nat.packed, u_py.packed)


def test_wide_pack_grow_on_overflow():
    """Forcing > 32 rows into one bucket (same (src, dst)-hash home via a
    crafted load factor) must grow-and-retry, never corrupt: pack 200 rows
    at a table size of 4 buckets (50 expected per bucket > 32) and verify
    every key still resolves."""
    from reporter_tpu.tiles.ubodt import WIDE_BUCKET, ubodt_from_columns

    rng = np.random.default_rng(7)
    src, dst, dist, tm, fe = _random_columns(rng, 200)
    # load_factor > 1 forces an initial 4-bucket table; the packer must
    # detect the overflow and double until every bucket fits
    u = ubodt_from_columns(src, dst, dist, tm, fe, delta=1000.0,
                           layout="wide32", load_factor=50.0,
                           use_native=False)
    assert u.n_buckets > 4
    occupancy = (u.packed[:, :, 0] != -1).sum(axis=1)
    assert occupancy.max() <= WIDE_BUCKET
    for i in range(0, 200, 17):
        d, t, f = u.lookup_full(int(src[i]), int(dst[i]))
        assert d == pytest.approx(float(dist[i]), rel=1e-6)
        assert f == int(fe[i])


@pytest.mark.parametrize("seed", [11, 12])
def test_layout_probe_equivalence_roundtrip(seed):
    """Property-based round-trip: the SAME rows packed into both layouts
    must answer every lookup identically — hits bit-for-bit (the stored
    f32 payloads), misses as misses — on host and on device, with dedup
    on and off."""
    import jax.numpy as jnp

    from reporter_tpu.ops.hashtable import ubodt_lookup
    from reporter_tpu.tiles.ubodt import ubodt_from_columns

    rng = np.random.default_rng(seed)
    src, dst, dist, tm, fe = _random_columns(rng, 3000)
    u_c = ubodt_from_columns(src, dst, dist, tm, fe, delta=1000.0,
                             layout="cuckoo")
    u_w = ubodt_from_columns(src, dst, dist, tm, fe, delta=1000.0,
                             layout="wide32")
    assert (u_c.max_probes, u_w.max_probes) == (2, 1)

    # host probes: every packed key + guaranteed misses
    for i in range(0, 3000, 113):
        assert u_c.lookup_full(int(src[i]), int(dst[i])) == \
            u_w.lookup_full(int(src[i]), int(dst[i]))
    assert u_w.lookup(int(src[0]), int(dst[0]) + 10_000_001)[0] == float("inf")

    # device probes over a duplicate-heavy query set (dedup's home turf):
    # half real keys (some repeated), half random misses
    du_c, du_w = u_c.to_device(), u_w.to_device()
    qs = np.concatenate([src[rng.integers(0, 3000, 2048)],
                         rng.integers(0, 1 << 24, 2048).astype(np.int32)])
    qd = np.concatenate([dst[rng.integers(0, 3000, 2048)],
                         rng.integers(0, 1 << 24, 2048).astype(np.int32)])
    results = {}
    for layout, du in (("cuckoo", du_c), ("wide32", du_w)):
        for dedup in (False, True):
            r = ubodt_lookup(du, jnp.asarray(qs), jnp.asarray(qd),
                             dedup=dedup)
            results[(layout, dedup)] = tuple(np.asarray(x) for x in r)
    base = results[("cuckoo", False)]
    for key, r in results.items():
        for i in range(3):
            np.testing.assert_array_equal(r[i], base[i], err_msg=str(key))


def test_dedup_overflow_fallback_exact():
    """When a batch's distinct-pair count exceeds the static dedup budget
    (all-distinct keys), the in-program fallback must return exactly the
    plain probe's results — the truncation edge case of the dedup path."""
    import jax.numpy as jnp

    from reporter_tpu.ops.hashtable import (
        _DEDUP_MIN_PAIRS, ubodt_lookup)
    from reporter_tpu.tiles.ubodt import ubodt_from_columns

    rng = np.random.default_rng(21)
    src, dst, dist, tm, fe = _random_columns(rng, 4000)
    u = ubodt_from_columns(src, dst, dist, tm, fe, delta=1000.0,
                           layout="wide32")
    du = u.to_device()
    n = max(2 * _DEDUP_MIN_PAIRS, 4000)
    qs = src[np.arange(n) % 4000]
    qd = dst[np.arange(n) % 4000]  # aligned -> all-hit, all-distinct
    r_d = ubodt_lookup(du, jnp.asarray(qs), jnp.asarray(qd), dedup=True)
    r_p = ubodt_lookup(du, jnp.asarray(qs), jnp.asarray(qd), dedup=False)
    for a, b in zip(r_d, r_p):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_relayout_preserves_content():
    """relayout() repacks rows without a graph re-search: content-identical
    lookups, layout-appropriate probe bound, original left untouched."""
    from reporter_tpu.tiles.ubodt import ubodt_from_columns

    rng = np.random.default_rng(31)
    src, dst, dist, tm, fe = _random_columns(rng, 1000)
    u_c = ubodt_from_columns(src, dst, dist, tm, fe, delta=750.0)
    u_w = u_c.relayout("wide32")
    assert u_c.layout == "cuckoo" and u_w.layout == "wide32"
    assert u_w.delta == u_c.delta and u_w.num_rows == u_c.num_rows
    assert u_w.relayout("wide32") is u_w  # no-op when layouts match
    back = u_w.relayout("cuckoo")
    for i in range(0, 1000, 41):
        want = u_c.lookup_full(int(src[i]), int(dst[i]))
        assert u_w.lookup_full(int(src[i]), int(dst[i])) == want
        assert back.lookup_full(int(src[i]), int(dst[i])) == want
