"""ubodt_probe_stats: the delta-bound coverage counter (ops/diagnostics)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from reporter_tpu.matching import MatcherConfig
from reporter_tpu.ops.diagnostics import ubodt_probe_stats
from reporter_tpu.ops.viterbi import MatchParams, pack_inputs
from reporter_tpu.synth import TraceSynthesizer
from reporter_tpu.synth.generator import cohort_xy
from reporter_tpu.tiles.arrays import build_graph_arrays
from reporter_tpu.tiles.network import grid_city
from reporter_tpu.tiles.ubodt import build_ubodt


@pytest.fixture(scope="module")
def city():
    net = grid_city(rows=10, cols=10, spacing_m=200.0)
    arrays = build_graph_arrays(net, cell_size=100.0)
    return net, arrays


def _stats(arrays, ubodt, cfg, straces, T, delta):
    dg = arrays.to_device()
    du = ubodt.to_device()
    p = MatchParams.from_config(cfg)
    px, py, tm, valid = cohort_xy(arrays, straces, T)
    xin = jnp.asarray(pack_inputs(px, py, tm, valid))
    return np.asarray(
        jax.jit(ubodt_probe_stats, static_argnums=(4,))(
            dg, du, xin, p, cfg.beam_k, delta)
    )


def test_full_delta_has_low_miss_rate(city):
    """With delta covering the whole city, almost no probe can miss for
    delta reasons (remaining misses are genuine no-path pairs)."""
    net, arrays = city
    cfg = MatcherConfig(ubodt_delta=10000.0)
    ubodt = build_ubodt(arrays, delta=10000.0)
    synth = TraceSynthesizer(arrays, seed=3)
    stats = _stats(
        arrays, ubodt, cfg, synth.batch(8, 32, dt=5.0, sigma=3.0), 32, 10000.0)
    pairs, miss, costly, beyond, distinct = (int(v) for v in stats)
    assert pairs > 0
    # no hop is provably beyond a 10 km table on a ~2 km city
    assert beyond == 0
    # dense sampling on a connected grid: nearly every probe is answerable
    assert costly / pairs < 0.05
    # the redundancy diagnostic: distinct pairs are a (much smaller)
    # subset of probed pairs on road-following fleets — the headroom the
    # in-batch probe dedup exploits (docs/performance.md)
    assert 0 < distinct <= pairs
    assert pairs / distinct > 2.0


def test_tiny_delta_drives_misses_up(city):
    """Shrinking delta below the sampling gap turns answerable probes into
    costly misses (forced transition breaks), and most become PROVABLE
    truncations (gc > delta) -- the accuracy bound the counter surfaces."""
    net, arrays = city
    synth = TraceSynthesizer(arrays, seed=3)
    traces = synth.batch(8, 32, dt=30.0, sigma=3.0)  # sparse: ~300+ m hops

    def fracs(delta):
        cfg = MatcherConfig(ubodt_delta=delta)
        ubodt = build_ubodt(arrays, delta=delta)
        stats = _stats(arrays, ubodt, cfg, traces, 32, delta)
        pairs = max(int(stats[0]), 1)
        return int(stats[2]) / pairs, int(stats[3]) / pairs

    costly_low, trunc_low = fracs(6000.0)
    costly_high, trunc_high = fracs(300.0)
    assert costly_high > costly_low
    assert costly_high > 0.1  # a 300 m table cannot answer 300+ m hops
    assert trunc_high > 0.05  # and many misses are provably the bound's fault
    assert trunc_low == 0.0  # no 30 s hop exceeds a 6 km table's reach
