"""Stream-state checkpoint/restore round-trips.

State captured mid-stream, restored into a fresh pipeline, and the combined
run must produce the same tiles as an uninterrupted one (the Kafka
state-store durability contract the reference gets from changelog topics).
"""

import os

import pytest

from reporter_tpu.stream.anonymiser import AnonymisingProcessor
from reporter_tpu.stream.batcher import BatchingProcessor
from reporter_tpu.stream.checkpoint import load_file, save_file
from reporter_tpu.stream.formatter import Formatter
from reporter_tpu.stream.topology import StreamPipeline


class NullClient:
    """Matcher client that never reports (keeps everything in-flight)."""

    def report_many(self, requests):
        return [None] * len(requests)


def _pipeline(tmp_path, out_name):
    out = tmp_path / out_name
    out.mkdir(exist_ok=True)
    anon = AnonymisingProcessor(
        privacy=1, quantisation=3600, output=str(out), source="CKPT",
        flush_interval_sec=3600,
    )
    batcher = BatchingProcessor(
        client=NullClient(),
        sink=lambda key, seg: anon.process(key, seg),
        microbatch_size=1000,  # never flush during the test
    )
    fmt = Formatter.from_config(",sv,\\|,0,2,3,1,4")
    return StreamPipeline(fmt, batcher, anon)


def _feed(p, n, t0=1_460_000_000):
    for i in range(n):
        p.feed("veh-%d|%d|37.75|%0.6f|5" % (i % 3, t0 + i * 5, -122.44 + i * 1e-4),
               (t0 + i * 5) * 1000)


def test_roundtrip_preserves_inflight_state(tmp_path):
    p1 = _pipeline(tmp_path, "out1")
    _feed(p1, 9)
    ck = str(tmp_path / "state.ckpt")
    save_file(p1, ck)
    assert os.path.exists(ck)

    p2 = _pipeline(tmp_path, "out2")
    assert load_file(p2, ck)

    assert set(p2.batcher.store) == set(p1.batcher.store)
    for k in p1.batcher.store:
        a, b = p1.batcher.store[k], p2.batcher.store[k]
        assert len(a.points) == len(b.points)
        # the binary serde stores max_separation as f32 (fixed layout,
        # Batch.java:92-146 parity) -- compare at that precision
        import numpy as np

        assert np.float32(a.max_separation) == np.float32(b.max_separation)
        assert a.last_update == b.last_update
        assert [p.pack() for p in a.points] == [p.pack() for p in b.points]
    assert p2.formatted == p1.formatted
    assert p2.anonymiser.map == p1.anonymiser.map


def test_missing_file_is_clean_boot(tmp_path):
    p = _pipeline(tmp_path, "out3")
    assert not load_file(p, str(tmp_path / "nope.ckpt"))
    assert p.batcher.store == {}


def test_version_mismatch_rejected(tmp_path):
    import json

    from reporter_tpu.stream.checkpoint import restore

    p = _pipeline(tmp_path, "out4")
    with pytest.raises(ValueError):
        restore(p, {"version": 99})


def test_corrupt_checkpoint_boots_clean_and_sets_file_aside(tmp_path):
    """A corrupt checkpoint must not crash-loop the CLI boot: load_file
    rolls back to clean state, renames the file to .corrupt, returns
    False -- and the next boot doesn't see it again."""
    import json

    p1 = _pipeline(tmp_path, "out5")
    _feed(p1, 6)
    ck = str(tmp_path / "state.ckpt")
    save_file(p1, ck)

    # mid-restore failure: valid version + counters + batcher block, then
    # an unparseable anonymiser slice -- restore() mutates dropped/_ready/
    # reported_pairs before it fails, so the rollback must cover them all
    partial = json.loads(open(ck).read())
    partial["dropped"] = 7
    partial["batcher"]["reported_pairs"] = 9
    partial["anonymiser"]["slices"] = {"t": "!!!notbase64"}

    for payload in (b"{truncated", b"\x00\xff\x00garbage",
                    json.dumps({"version": 99}).encode(),
                    json.dumps({"version": 1, "batcher": 42}).encode(),
                    json.dumps(partial).encode()):
        with open(ck, "wb") as f:
            f.write(payload)
        p2 = _pipeline(tmp_path, "out5b")
        assert load_file(p2, ck) is False
        assert p2.batcher.store == {}  # rolled back / clean
        assert p2.dropped == 0 and p2.batcher.reported_pairs == 0
        assert p2.batcher._ready == []
        assert os.path.exists(ck + ".corrupt")
        assert not os.path.exists(ck)
        # second boot: the bad file is gone, clean boot without noise
        p3 = _pipeline(tmp_path, "out5c")
        assert load_file(p3, ck) is False
        os.remove(ck + ".corrupt")


# -- crash recovery: SIGKILL mid-checkpoint-flush ---------------------------

_DRIVER = r'''
import sys

from reporter_tpu.stream.anonymiser import AnonymisingProcessor
from reporter_tpu.stream.batcher import BatchingProcessor
from reporter_tpu.stream.checkpoint import load_file, save_file
from reporter_tpu.stream.formatter import Formatter
from reporter_tpu.stream.topology import StreamPipeline

records_path, ckpt, outdir = sys.argv[1:4]


class StubClient:
    """Deterministic matcher stand-in: one synthetic segment pair per
    consecutive point pair, derived purely from the request — so an
    uninterrupted run and a killed+resumed run must emit identical tiles
    unless the checkpoint seam loses or duplicates state."""

    def report_many(self, requests):
        out = []
        for r in requests:
            pts = r["trace"]
            uid = int("".join(c for c in r["uuid"] if c.isdigit()) or 0)
            reports = [
                {"id": 1000 * (uid + 1) + i, "next_id": 1000 * (uid + 1) + i + 1,
                 "t0": float(pts[i]["time"]), "t1": float(pts[i + 1]["time"]),
                 "length": 120, "queue_length": 0}
                for i in range(len(pts) - 1)
            ]
            out.append({"datastore": {"reports": reports},
                        "shape_used": len(pts) - 1})
        return out


anon = AnonymisingProcessor(privacy=1, quantisation=3600, output=outdir,
                            source="CKPT", flush_interval_sec=10 ** 9)
batcher = BatchingProcessor(
    client=StubClient(), sink=lambda k, s: anon.process(k, s),
    microbatch_size=4, report_dist=0, report_count=4, report_time=0)
pipe = StreamPipeline(Formatter.from_config(",sv,\\|,0,2,3,1,4"),
                      batcher, anon)
load_file(pipe, ckpt)  # resume when a snapshot exists, else clean boot
records = [l for l in open(records_path).read().splitlines() if l]
# the snapshot itself carries the committed offset (formatted + dropped
# ride it), so state and offset can never diverge: atomic tmp+rename
start = pipe.formatted + pipe.dropped
for i in range(start, len(records)):
    pipe.feed(records[i], 1_460_000_000_000 + i)
    save_file(pipe, ckpt)  # checkpoint per record: the kill lands mid-flush
    print("FED %d" % (i + 1), flush=True)
pipe.close()
print("DONE", flush=True)
'''


def _tile_rows(outdir):
    """Multiset of CSV rows across every flushed tile file (file names are
    uuid4-suffixed, so only the rows are comparable)."""
    import collections

    rows = collections.Counter()
    for root, _dirs, files in os.walk(outdir):
        for fn in files:
            with open(os.path.join(root, fn)) as f:
                for line in f.read().splitlines():
                    if line and not line.startswith("segment_id"):
                        rows[line] += 1
    return rows


def test_sigkill_mid_checkpoint_flush_recovers_exactly_once(tmp_path):
    """Crash-recovery across the resume seam: a driver feeding records and
    checkpointing after each one is SIGKILLed (likely mid save_file, whose
    tmp+rename must stay atomic), restarted against the same checkpoint,
    and run to completion.  The flushed tiles must equal an uninterrupted
    run's EXACTLY — no lost windows, no duplicated windows."""
    import signal
    import subprocess
    import sys

    driver = tmp_path / "driver.py"
    driver.write_text(_DRIVER)
    records = []
    for i in range(36):
        records.append("veh-%d|%d|%0.6f|%0.6f|5" % (
            i % 3, 1_460_000_000 + (i // 3) * 15,
            37.75 + (i // 3) * 5e-3, -122.44 + (i // 3) * 5e-3))
    rec_path = tmp_path / "records.txt"
    rec_path.write_text("\n".join(records) + "\n")
    env = dict(os.environ, PYTHONPATH=os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

    def run(ckpt, outdir, kill_at=None):
        os.makedirs(outdir, exist_ok=True)
        proc = subprocess.Popen(
            [sys.executable, str(driver), str(rec_path), ckpt, outdir],
            stdout=subprocess.PIPE, text=True, env=env)
        try:
            for line in proc.stdout:
                if kill_at is not None and line.startswith("FED"):
                    if int(line.split()[1]) >= kill_at:
                        # SIGKILL with the next feed+checkpoint already in
                        # flight: no atexit, no flush, no goodbye
                        proc.send_signal(signal.SIGKILL)
                        proc.wait()
                        return None
                if line.startswith("DONE"):
                    proc.wait()
                    return True
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait()
        return proc.returncode == 0

    # reference: one uninterrupted run
    assert run(str(tmp_path / "ref.ckpt"), str(tmp_path / "ref_out")) is True
    expected = _tile_rows(str(tmp_path / "ref_out"))
    assert expected, "reference run flushed no tiles; test is vacuous"

    # chaos runs: kill at two different depths, resume, compare
    for kill_at, name in ((7, "k7"), (29, "k29")):
        ckpt = str(tmp_path / ("%s.ckpt" % name))
        outdir = str(tmp_path / ("%s_out" % name))
        assert run(ckpt, outdir, kill_at=kill_at) is None  # died by SIGKILL
        assert run(ckpt, outdir) is True  # resumed from the snapshot
        got = _tile_rows(outdir)
        assert got == expected, (
            "resume seam lost or duplicated windows (kill_at=%d)" % kill_at)


def test_corrupt_partition_checkpoint_boots_partition_clean(tmp_path):
    """The consumer-group path has the same seam: a bad part-N.ckpt must
    not crash-loop every rebalance that assigns partition N."""
    from reporter_tpu.stream.checkpoint import PartitionCheckpointer

    p = _pipeline(tmp_path, "out6")
    ck = PartitionCheckpointer(p, str(tmp_path / "parts"))
    bad = ck._path(3)
    with open(bad, "wb") as f:
        f.write(b"{nope")
    assert ck.load(3) == 0
    assert os.path.exists(bad + ".corrupt")
    assert not os.path.exists(bad)
    assert ck.load(3) == 0  # second rebalance: clean, no file
