"""Warmup / compile-stall elimination (docs/performance.md).

The acceptance contract: with a full warmup pass (serve --warmup semantics)
and a persistent XLA cache dir configured, a (re)started server's first
/report request records ZERO compile_stall events for configured buckets —
every first-dispatch compile is paid in the warmup phase, visible in the
warmup counters, and the request-path compile counters stay flat across
real traffic on warmed shapes.  Asserted via the obs registry
(reporter_compile_total / reporter_warmup_shapes_total).
"""

import numpy as np
import pytest

from reporter_tpu.matching import MatcherConfig, SegmentMatcher
from reporter_tpu.obs import metrics as obs
from reporter_tpu.tiles.arrays import build_graph_arrays
from reporter_tpu.tiles.network import grid_city
from reporter_tpu.tiles.ubodt import build_ubodt


@pytest.fixture(scope="module")
def engine():
    city = grid_city(rows=5, cols=5, spacing_m=150.0)
    arrays = build_graph_arrays(city, cell_size=100.0)
    ubodt = build_ubodt(arrays, delta=2000.0)
    return arrays, ubodt


CFG = dict(length_buckets=[16, 32], warmup_batch_sizes=[1])


def _compile_total() -> float:
    snap = obs.REGISTRY.snapshot().get(
        "reporter_compile_total", {"samples": []})
    return sum(v for _lv, v in snap["samples"])


def _warm_shapes_total() -> float:
    snap = obs.REGISTRY.snapshot().get(
        "reporter_warmup_shapes_total", {"samples": []})
    return sum(v for _lv, v in snap["samples"])


def _trace(arrays, n=10, uuid="wm"):
    xs = np.linspace(float(arrays.node_x.min()) + 5.0,
                     float(arrays.node_x.max()) - 5.0, n)
    ys = np.full(n, float(arrays.node_y.min()) + 1.0)
    lat, lon = arrays.proj.to_latlon(xs, ys)
    return {
        "uuid": uuid,
        "match_options": {"mode": "auto", "report_levels": [0, 1],
                          "transition_levels": [0, 1]},
        "trace": [{"lat": float(a), "lon": float(o), "time": 1000 + 5 * i,
                   "accuracy": 5} for i, (a, o) in enumerate(zip(lat, lon))],
    }


def test_warmed_server_first_request_sees_no_compile_stall(engine, tmp_path, monkeypatch):
    """serve --warmup + REPORTER_XLA_CACHE_DIR: after the warm pass, the
    first real request of every configured bucket records zero new
    compile_stall events — across a simulated restart too."""
    monkeypatch.setenv("REPORTER_XLA_CACHE_DIR", str(tmp_path / "xla"))
    from reporter_tpu.utils.jaxenv import enable_compilation_cache

    assert enable_compilation_cache() == str(tmp_path / "xla")

    arrays, ubodt = engine
    for restart in range(2):  # second round = the restarted server
        matcher = SegmentMatcher(
            arrays=arrays, ubodt=ubodt, config=MatcherConfig(**CFG))
        warmed_before = _warm_shapes_total()
        matcher.warmup()
        assert _warm_shapes_total() > warmed_before
        for bucket in matcher.cfg.length_buckets:
            assert matcher.compiled_shape_count(bucket) > 0, (restart, bucket)

        from reporter_tpu.serve.service import ReporterService

        service = ReporterService(matcher, max_wait_ms=1.0)
        before = _compile_total()
        for n in (10, 16, 30):  # both configured buckets, first requests
            code, data = service.handle_report(_trace(arrays, n))
            assert code == 200, data
        assert _compile_total() == before, (
            "restart %d: a warmed bucket paid a request-path compile stall"
            % restart)


def test_unwarmed_request_does_record_compile(engine):
    """Control: without warmup the first request of a bucket IS a compile
    stall — the counter the warmed path must keep flat actually fires."""
    arrays, ubodt = engine
    matcher = SegmentMatcher(
        arrays=arrays, ubodt=ubodt, config=MatcherConfig(**CFG))
    before = _compile_total()
    matcher.match_many([_trace(arrays, 10)])
    assert _compile_total() == before + 1


def test_warmup_covers_kernels_and_batch_rungs(engine, monkeypatch):
    """warmup(kernels=..., batch_sizes=...) pre-dispatches the full
    (B, T, kernel) grid, and auto mode warms exactly the kernels live
    traffic will pick per bucket."""
    # auto-mode behaviour under test: the assoc-forcing CI leg must not
    # override the config this test pins
    monkeypatch.delenv("REPORTER_VITERBI", raising=False)
    arrays, ubodt = engine
    matcher = SegmentMatcher(
        arrays=arrays, ubodt=ubodt,
        config=MatcherConfig(viterbi_kernel="auto", viterbi_assoc_threshold=32,
                             length_buckets=[16, 32], warmup_batch_sizes=[1, 4]))
    matcher.warmup()
    # auto: bucket 16 -> scan, bucket 32 -> assoc; two rungs each
    assert matcher.compiled_shape_count(16, kernel="scan") == 2
    assert matcher.compiled_shape_count(32, kernel="assoc") == 2
    before = _compile_total()
    matcher.match_many([_trace(arrays, 12, uuid="a%d" % i) for i in range(3)])
    matcher.match_many([_trace(arrays, 28, uuid="b%d" % i) for i in range(2)])
    assert _compile_total() == before

    # explicit kernels warm both forwards for the same shapes
    m2 = SegmentMatcher(
        arrays=arrays, ubodt=ubodt, config=MatcherConfig(**CFG))
    m2.warmup(lengths=[16], kernels=("scan", "assoc"))
    assert m2.compiled_shape_count(16, kernel="scan") == 1
    assert m2.compiled_shape_count(16, kernel="assoc") == 1


def test_warmup_carry_chain_covers_streaming(engine):
    arrays, ubodt = engine
    matcher = SegmentMatcher(
        arrays=arrays, ubodt=ubodt, config=MatcherConfig(**CFG))
    matcher.warmup(carry_chain=True)
    before = _compile_total()
    matcher.match_many([_trace(arrays, 80)])  # > largest bucket: carry chain
    assert _compile_total() == before, "carry chain paid a request-path compile"


def test_warmup_covers_batched_precompute(engine, monkeypatch):
    """The hoisted long-trace path dispatches TWO programs per group — the
    chunk-batched precompute ("pre", kernel-independent) and the score
    recursion ("chain") — and warmup(carry_chain=True) must cover both:
    zero request-path compiles for a streamed long trace, across every
    chunk count whose pre rows snap to the warmed ladder rung."""
    monkeypatch.delenv("REPORTER_LONG_PRECOMPUTE", raising=False)
    arrays, ubodt = engine
    matcher = SegmentMatcher(
        arrays=arrays, ubodt=ubodt, config=MatcherConfig(**CFG))
    assert matcher._long_pre, "hoisted mode must be the default"
    matcher.warmup(carry_chain=True)
    W = matcher.cfg.length_buckets[-1]
    assert matcher.compiled_shape_count(W, kind="pre", kernel="none") > 0, (
        "warmup did not pre-dispatch the batched-precompute program")
    assert matcher.compiled_shape_count(W, kind="chain") > 0, (
        "warmup did not pre-dispatch the chain program")
    assert matcher.compiled_shape_count(W, kind="carry") == 0, (
        "hoisted mode compiled the legacy fused carry program")
    before = _compile_total()
    # 2, 3 and 4 chunks all share the warmed pre rung (rows 2..4 -> rung 4)
    # and the [1, W] chain shape: first requests must be compile-free
    for n in (2 * W + 9, 3 * W - 1, 4 * W - 2):
        matcher.match_many([_trace(arrays, n)])
    assert _compile_total() == before, (
        "a warmed long trace paid a request-path compile")


def test_warmup_session_step_covers_streaming(engine):
    """warmup(session_step=True) — the serve --warmup semantics — must
    pre-dispatch every (batch rung, session bucket) incremental-step
    shape, so the FIRST streaming point of a fresh boot records zero
    request-path compile stalls (the session matcher's whole point is
    point latency; an inline XLA compile there is the stall the carry
    chain already eliminated for long traces)."""
    from reporter_tpu.matching.session import SessionEngine, SessionStore
    from reporter_tpu.serve.service import ReporterService

    arrays, ubodt = engine
    matcher = SegmentMatcher(
        arrays=arrays, ubodt=ubodt,
        config=MatcherConfig(session_buckets=[4, 16], **CFG))
    matcher.warmup(lengths=[], session_step=True)
    for w in matcher.cfg.session_buckets:
        assert matcher.compiled_shape_count(w, kind="session") > 0, w
    before = _compile_total()
    # the real streaming submit path: single point (bucket 4), then a
    # wider delta (bucket 16) — both warmed, neither may compile
    service = ReporterService(matcher, max_wait_ms=1.0, session_wait_ms=1.0)
    tr = _trace(arrays, 12, uuid="wm-stream")
    code, data = service.handle_report(
        dict(tr, stream=True, trace=tr["trace"][:1]))
    assert code == 200, data
    code, data = service.handle_report(
        dict(tr, stream=True, trace=tr["trace"][1:]))
    assert code == 200, data
    assert _compile_total() == before, (
        "a warmed session step paid a request-path compile stall")

    # control: an UNwarmed matcher's first streaming step IS a compile
    m2 = SegmentMatcher(
        arrays=arrays, ubodt=ubodt,
        config=MatcherConfig(session_buckets=[4, 16], **CFG))
    eng = SessionEngine(m2, SessionStore(), tail_points=64)
    before = _compile_total()
    eng.match_many([{"uuid": "wm-cold", "trace": tr["trace"][:1],
                     "match_options": tr["match_options"]}])
    assert _compile_total() == before + 1


@pytest.mark.slow  # the mesh rehearsal leg boots serve --warmup on the dp-8 topology
def test_warmup_covers_mesh_programs(engine):
    """A mesh matcher's warmup (serve --warmup on the pod topology,
    docs/performance.md "One logical matcher per pod") pre-dispatches the
    dp-sharded program variants through the REAL dispatch path, so the
    first requests of a warmed mesh replica — bucketed, carry-chain long,
    and streaming session step — pay zero request-path compiles."""
    import jax

    from reporter_tpu.matching.session import SessionEngine, SessionStore

    if len(jax.devices()) < 2:
        pytest.skip("needs the virtual multi-device CPU backend")
    arrays, ubodt = engine
    matcher = SegmentMatcher(
        arrays=arrays, ubodt=ubodt,
        config=MatcherConfig(devices=2, session_buckets=[4, 16], **CFG))
    assert matcher._mesh is not None
    matcher.warmup(carry_chain=True, session_step=True)
    before = _compile_total()
    matcher.match_many([_trace(arrays, 12, uuid="mesh-a")])
    matcher.match_many([_trace(arrays, 80, uuid="mesh-b")])  # carry chain
    eng = SessionEngine(matcher, SessionStore(), tail_points=64)
    tr = _trace(arrays, 12, uuid="mesh-stream")
    eng.match_many([{"uuid": tr["uuid"], "trace": tr["trace"][:1],
                     "match_options": tr["match_options"]}])
    assert _compile_total() == before, (
        "a warmed mesh program paid a request-path compile stall")


def test_legacy_long_path_still_selectable(engine, monkeypatch):
    """REPORTER_LONG_PRECOMPUTE=0 forces the legacy fused per-chunk carry
    program — the differential reference must stay dispatchable."""
    monkeypatch.setenv("REPORTER_LONG_PRECOMPUTE", "0")
    arrays, ubodt = engine
    matcher = SegmentMatcher(
        arrays=arrays, ubodt=ubodt, config=MatcherConfig(**CFG))
    assert not matcher._long_pre
    out = matcher.match_many([_trace(arrays, 80)])
    assert out[0]["segments"]
    assert any(k[0] == "carry" for k in matcher._compiled_shapes)
    assert all(k[0] not in ("pre", "chain") for k in matcher._compiled_shapes)


def test_stage_rows_reuses_pinned_buffers(engine):
    """The batch-pad hot path must stop reallocating: same shape in, same
    staging buffer out, with the pad tail re-zeroed between uses."""
    arrays, ubodt = engine
    matcher = SegmentMatcher(
        arrays=arrays, ubodt=ubodt, config=MatcherConfig(**CFG))
    a = np.ones((3, 16), np.float32)
    out1 = matcher._stage_rows(4, a, a * 2.0)
    buf_ids = [id(o) for o in out1]
    assert all(o.shape == (4, 16) for o in out1)
    assert (out1[0][3] == 0).all()
    # poison the tail, restage: same buffers, tail re-zeroed
    out1[1][3] = 7.0
    b = np.full((2, 16), 5.0, np.float32)
    out2 = matcher._stage_rows(4, b, b)
    assert [id(o) for o in out2] == buf_ids
    assert (out2[1][2:] == 0).all() and (out2[1][:2] == 5.0).all()
    # distinct slots never share a buffer even at identical shape/dtype
    assert id(out2[0]) != id(out2[1])


def test_probe_stats_deferred_off_dispatch(engine, monkeypatch):
    """The sampled UBODT probe is dispatched on the hot thread but its
    np.asarray sync happens on the collect side: after a dispatch tick the
    probe sits in _probe_pending; the collect drains it into the outcome
    counters."""
    monkeypatch.setenv("REPORTER_OBS_PROBE_EVERY", "1")
    arrays, ubodt = engine
    matcher = SegmentMatcher(
        arrays=arrays, ubodt=ubodt, config=MatcherConfig(**CFG))
    assert matcher._probe_every == 1

    def _pairs_total():
        snap = obs.REGISTRY.snapshot().get(
            "reporter_ubodt_probe_total", {"samples": []})
        return sum(v for lv, v in snap["samples"] if lv == ["pairs"])

    before = _pairs_total()
    t = _trace(arrays, 10)
    px, py, tm, valid, _times = matcher._fill_rows([t], [0], 16)
    handle = matcher._dispatch_batch(px, py, tm, valid)
    assert len(matcher._probe_pending) == 1, "probe sync ran on dispatch"
    matcher._collect_batch(handle)
    assert not matcher._probe_pending
    assert _pairs_total() > before
