"""tools/perf_gate.py — the noise-aware like-provenance regression gate.

Pins the behaviours the CI leg relies on: noise thresholds (incl. the
history-spread widening), provenance filtering (platform / scenario
scale / corpse artifacts), the explicit missing-history verdict, and the
round-6 schema assertions (--require-attrib)."""

import importlib.util
import json
import os

import pytest


def _load():
    path = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "perf_gate.py")
    spec = importlib.util.spec_from_file_location("perf_gate", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def pg():
    return _load()


def _line(platform="cpu", value=20.0, pps=2500.0, vsb=2.0, edges=50_000,
          **extra):
    out = {
        "metric": "traces_matched_per_sec_per_chip", "value": value,
        "unit": "traces/s", "platform": platform, "points_per_sec": pps,
        "vs_baseline": vsb, "edges": edges, "scenario": "osm",
        "last_onchip": None, "attrib": {"stages_ms_by_cohort": {}},
    }
    out.update(extra)
    return out


def _write(tmp_path, name, line, wrap_rc=None):
    p = tmp_path / name
    if wrap_rc is None:
        p.write_text(json.dumps(line))
    else:
        p.write_text(json.dumps({"n": 1, "rc": wrap_rc, "parsed": line,
                                 "tail": ""}))
    return str(p)


def test_loads_both_artifact_shapes(pg, tmp_path):
    raw = _write(tmp_path, "raw.json", _line())
    wrapped = _write(tmp_path, "wrap.json", _line(), wrap_rc=0)
    assert pg.load_bench_line(raw)["value"] == 20.0
    w = pg.load_bench_line(wrapped)
    assert w["value"] == 20.0 and w["_rc"] == 0


def test_regression_detected(pg, tmp_path):
    hist = [_write(tmp_path, "h%d.json" % i, _line(pps=2500.0 + i, vsb=2.0))
            for i in range(3)]
    fresh = _write(tmp_path, "fresh.json", _line(pps=1000.0, vsb=0.8))
    rc, verdict = pg.gate(hist, fresh)
    assert rc == 1
    assert verdict["verdict"] == "REGRESSION"
    assert verdict["metrics"]["points_per_sec"]["verdict"] == "REGRESSION"


def test_within_noise_passes(pg, tmp_path):
    hist = [_write(tmp_path, "h%d.json" % i, _line(pps=2500.0, vsb=2.0))
            for i in range(3)]
    # 20% below median is inside the wide CPU default (40%)
    fresh = _write(tmp_path, "fresh.json", _line(pps=2000.0, vsb=1.7))
    rc, verdict = pg.gate(hist, fresh)
    assert rc == 0
    assert verdict["verdict"] == "OK"


def test_history_spread_widens_threshold(pg, tmp_path):
    # history disagrees with itself by 2x: a fresh run 40% below the
    # median must NOT fail even past the CLI threshold
    hist = [_write(tmp_path, "h0.json", _line(pps=1500.0)),
            _write(tmp_path, "h1.json", _line(pps=2500.0)),
            _write(tmp_path, "h2.json", _line(pps=3500.0))]
    fresh = _write(tmp_path, "fresh.json", _line(pps=1500.0, vsb=2.0))
    rc, verdict = pg.gate(hist, fresh, threshold=0.10)
    assert rc == 0, verdict
    m = verdict["metrics"]["points_per_sec"]
    assert m["threshold"] > 0.10  # widened by the observed spread


def test_cpu_never_judged_against_tpu(pg, tmp_path):
    hist = [_write(tmp_path, "h0.json", _line(platform="tpu", pps=400_000.0))]
    fresh = _write(tmp_path, "fresh.json", _line(platform="cpu", pps=2000.0))
    rc, verdict = pg.gate(hist, fresh)
    assert rc == 0
    assert verdict["verdict"] == "NO-LIKE-PROVENANCE-HISTORY"
    assert "platform" in verdict["excluded"][0]["reason"]


def test_scale_mismatch_excluded(pg, tmp_path):
    hist = [_write(tmp_path, "h0.json", _line(edges=400))]  # smoke-scale
    fresh = _write(tmp_path, "fresh.json", _line(edges=50_000))
    rc, verdict = pg.gate(hist, fresh)
    assert rc == 0
    assert verdict["verdict"] == "NO-LIKE-PROVENANCE-HISTORY"


def test_corpse_history_excluded(pg, tmp_path):
    good = _write(tmp_path, "h0.json", _line(pps=2500.0))
    corpse = _write(tmp_path, "h1.json", _line(pps=100.0), wrap_rc=124)
    fresh = _write(tmp_path, "fresh.json", _line(pps=2400.0))
    rc, verdict = pg.gate([good, corpse], fresh)
    assert rc == 0, verdict
    assert verdict["baselines"] == [good]
    assert any("corpse" in e["reason"] for e in verdict["excluded"])


def test_corpse_candidate_invalid(pg, tmp_path):
    hist = [_write(tmp_path, "h0.json", _line())]
    fresh = _write(tmp_path, "fresh.json", _line(), wrap_rc=124)
    rc, verdict = pg.gate(hist, fresh)
    assert rc == 2
    assert verdict["verdict"] == "INVALID"


def test_missing_history_is_explicit_pass(pg, tmp_path):
    fresh = _write(tmp_path, "fresh.json", _line())
    rc, verdict = pg.gate([], fresh)
    assert rc == 0
    assert verdict["verdict"] == "NO-LIKE-PROVENANCE-HISTORY"
    assert verdict["baselines"] == []


def test_schema_invalid_candidate(pg, tmp_path):
    bad = dict(_line())
    del bad["value"]
    fresh = _write(tmp_path, "fresh.json", bad)
    rc, verdict = pg.gate([], fresh)
    assert rc == 2
    assert "value" in verdict["error"]


def test_require_attrib_schema(pg, tmp_path):
    # missing attrib key entirely -> invalid under --require-attrib
    noattrib = {k: v for k, v in _line().items() if k != "attrib"}
    fresh = _write(tmp_path, "f1.json", noattrib)
    rc, verdict = pg.gate([], fresh, require_attrib=True)
    assert rc == 2 and "attrib" in verdict["error"]
    # an explicit null attrib needs a reason (the SIGTERM/no-result paths)
    fresh = _write(tmp_path, "f2.json", _line(attrib=None))
    rc, verdict = pg.gate([], fresh, require_attrib=True)
    assert rc == 2 and "attrib_reason" in verdict["error"]
    fresh = _write(tmp_path, "f3.json",
                   _line(attrib=None, attrib_reason="BENCH_PROFILE=0"))
    rc, _ = pg.gate([], fresh, require_attrib=True)
    assert rc == 0
    # without the flag, pre-round-6 lines stay judgeable
    rc, _ = pg.gate([], _write(tmp_path, "f4.json", noattrib))
    assert rc == 0


def test_candidate_defaults_to_last_positional(pg, tmp_path):
    h = _write(tmp_path, "h0.json", _line(pps=2500.0))
    f = _write(tmp_path, "f.json", _line(pps=100.0, vsb=0.1))
    rc, verdict = pg.gate([h, f])
    assert rc == 1
    assert verdict["candidate"] == f


def test_repo_history_renders_verdict(pg):
    """The acceptance-criteria invocation: perf_gate over the real
    BENCH_r0*.json bank renders a verdict (the newest round is an rc-124
    corpse — the gate must say so rather than judge it)."""
    import glob

    repo = os.path.join(os.path.dirname(__file__), "..")
    files = sorted(glob.glob(os.path.join(repo, "BENCH_r0*.json")))
    assert len(files) >= 2
    rc, verdict = pg.gate(files)
    assert verdict["verdict"] in ("OK", "REGRESSION", "INVALID",
                                  "NO-LIKE-PROVENANCE-HISTORY")
    # r05 specifically: the official 0.57x record is an rc-124 corpse and
    # must never pass the gate as a judgeable run
    if os.path.basename(verdict["candidate"]) == "BENCH_r05.json":
        assert rc == 2 and "corpse" in verdict["error"]


def test_host_pipeline_flattening_and_directions(pg, tmp_path):
    """The columnar-host-plane metrics: host_pack_points_per_sec and
    host_frac flatten out of the artifact host_pipeline block, pack rate
    regresses on a DROP and host_frac regresses on a RISE."""
    hp = {"pack": {"host_pack_points_per_sec": 1_000_000.0},
          "host_frac": 0.10}
    line = pg.load_bench_line(_write(
        tmp_path, "hp.json", _line(host_pipeline=hp)))
    assert line["host_pack_points_per_sec"] == 1_000_000.0
    assert line["host_frac"] == 0.10
    assert pg.METRICS["host_pack_points_per_sec"] == "higher"
    assert pg.METRICS["host_frac"] == "lower"

    hist = [_write(tmp_path, "hh%d.json" % i,
                   _line(host_pipeline=hp, host_frac=0.10))
            for i in range(3)]
    # pack rate collapse fails the gate
    slow = _line(host_pipeline={"pack": {"host_pack_points_per_sec": 1e5},
                                "host_frac": 0.10}, host_frac=0.10)
    rc, verdict = pg.gate(hist, _write(tmp_path, "f_slow.json", slow))
    assert rc == 1
    assert verdict["metrics"]["host_pack_points_per_sec"][
        "verdict"] == "REGRESSION"
    # host share creeping UP fails the gate (lower-is-better direction)
    hosty = _line(host_pipeline=hp, host_frac=0.60)
    rc, verdict = pg.gate(hist, _write(tmp_path, "f_hosty.json", hosty))
    assert rc == 1
    assert verdict["metrics"]["host_frac"]["verdict"] == "REGRESSION"
    # matching numbers pass
    rc, _ = pg.gate(hist, _write(tmp_path, "f_ok.json",
                                 _line(host_pipeline=hp, host_frac=0.10)))
    assert rc == 0
