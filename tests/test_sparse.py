"""Sparse-gap matching model (docs/match-quality.md "Sparse gaps").

Three contracts pinned here:

  1. FLAG-GATING — with the model disabled (the config default, or an
     explicit REPORTER_SPARSE=0 over a sparse-configured matcher) every
     wire byte equals the pre-sparse output, across both viterbi kernels
     x both UBODT layouts, including the per-vehicle session/streaming
     path; and with the model ENABLED, dense traffic is untouched (the
     sparse kinds are separate jit cache entries).

  2. THE MODEL — time-adaptive beta grows with the gap and caps;
     gap-conditioned breakage keeps honest ≥60 s teleports connected
     where the fixed rule restarts; the drivable-speed plausibility term
     (the measured lever of the calibration sweep) improves agreement
     against the brute-force f64 oracle on a sparse corpus, and the
     oracle speaks the same model (baseline/brute_matcher sparse=).

  3. THE PLANE — CALIBRATION.json loads per cohort (corrupt files
     degrade loudly to the config family), the silent radius clamp is
     now a counter + ?debug=1 flag, the route-consistent interpolation
     engine re-times intermediate segments by free-flow speed while
     keeping the record schema byte-compatible, and loadgen's
     --gap-jitter produces genuinely non-uniform gaps recorded in the
     realized-gap histogram.
"""

import dataclasses
import json

import numpy as np
import pytest

from reporter_tpu.matching import MatcherConfig, SegmentMatcher
from reporter_tpu.matching import sparse as sparse_mod
from reporter_tpu.synth import TraceSynthesizer
from reporter_tpu.synth.generator import dryrun_scenario


@pytest.fixture(autouse=True)
def _clean_sparse_env(monkeypatch):
    """The serve CLI entrypoint setdefaults REPORTER_SPARSE=1 /
    REPORTER_QUALITY_AUX=1 into the process env, and test_service runs it
    in-process earlier in the tier-1 order — the differential tests here
    need the LIBRARY defaults, so every test starts from a clean env."""
    for var in ("REPORTER_SPARSE", "REPORTER_QUALITY_AUX",
                "REPORTER_CALIBRATION", "REPORTER_INTERPOLATE"):
        monkeypatch.delenv(var, raising=False)


@pytest.fixture(scope="module")
def world():
    cfg, arrays, ubodt = dryrun_scenario(rows=6, cols=6, spacing_m=200.0,
                                         delta=3000.0)
    cfg = dataclasses.replace(cfg, length_buckets=[16, 32])
    return cfg, arrays, ubodt


def corpus(arrays, seed=5, dense_n=3, sparse_n=3):
    synth = TraceSynthesizer(arrays, seed=seed)
    traces = []
    for i in range(dense_n):
        traces.append(synth.synthesize(
            12, dt=5.0, uuid="dense-%d" % i, max_tries=60).trace)
    for i in range(sparse_n):
        traces.append(synth.synthesize(
            12, dt=60.0, uuid="sparse-%d" % i, max_tries=300).trace)
    return traces


def wire(results):
    return json.dumps(results, sort_keys=True)


# -- 1. flag-gating -----------------------------------------------------------

@pytest.mark.parametrize("kernel", ["scan", "assoc"])
@pytest.mark.parametrize("layout", ["cuckoo", "wide32"])
def test_sparse_off_bit_identical(world, kernel, layout, monkeypatch):
    """REPORTER_SPARSE=0 over a sparse-configured matcher reproduces the
    default matcher's wire output byte-for-byte — kernels x layouts."""
    cfg, arrays, ubodt = world
    cfg = dataclasses.replace(cfg, viterbi_kernel=kernel,
                              ubodt_layout=layout)
    traces = corpus(arrays)
    ref = SegmentMatcher(arrays=arrays, ubodt=ubodt, config=cfg)
    base = ref.match_many(traces)

    cfg_sp = dataclasses.replace(
        cfg, sparse=True, sparse_beam_k=16, sparse_beta_scale=1.0,
        sparse_vmax_mps=16.0)
    monkeypatch.setenv("REPORTER_SPARSE", "0")
    off = SegmentMatcher(arrays=arrays, ubodt=ubodt, config=cfg_sp)
    assert not off.sparse.enabled
    assert wire(off.match_many(traces)) == wire(base)


def test_sparse_on_dense_unchanged(world):
    """With the model ON, dense traces still dispatch the classic kind and
    their bytes are untouched; sparse-cohort traces actually change."""
    cfg, arrays, ubodt = world
    traces = corpus(arrays)
    base = SegmentMatcher(arrays=arrays, ubodt=ubodt,
                          config=cfg).match_many(traces)
    cfg_sp = dataclasses.replace(cfg, sparse=True, sparse_vmax_mps=16.0)
    on = SegmentMatcher(arrays=arrays, ubodt=ubodt, config=cfg_sp)
    assert on.sparse.enabled
    res = on.match_many(traces)
    for i in range(3):  # dense third: bit-identical
        assert wire([res[i]]) == wire([base[i]])
    assert any(wire([res[3 + i]]) != wire([base[3 + i]])
               for i in range(3)), "sparse model never engaged"
    assert sparse_mod.C_SPARSE_DISPATCH.labels("ge60").value > 0


@pytest.mark.parametrize("devices", [
    pytest.param(2, marks=pytest.mark.slow), 8])
def test_sparse_mesh_identical(world, devices):
    """The sparse cohort dispatch under a dp mesh (docs/performance.md
    "One logical matcher per pod"): mixed dense+sparse batches on N
    devices reproduce the 1-device sparse wire byte-for-byte."""
    import jax

    if len(jax.devices()) < devices:
        pytest.skip("needs >= %d virtual devices" % devices)
    cfg, arrays, ubodt = world
    traces = corpus(arrays)
    cfg_sp = dataclasses.replace(cfg, sparse=True, sparse_vmax_mps=16.0)
    want = wire(SegmentMatcher(arrays=arrays, ubodt=ubodt,
                               config=cfg_sp).match_many(traces))
    cfg_m = dataclasses.replace(cfg_sp, devices=devices)
    m = SegmentMatcher(arrays=arrays, ubodt=ubodt, config=cfg_m)
    assert m.sparse.enabled and m._mesh is not None
    assert wire(m.match_many(traces)) == want


def test_session_sparse_off_identical(world, monkeypatch):
    """The streaming path under REPORTER_SPARSE=0: bit-identical session
    step results (the satellite's session-path differential)."""
    cfg, arrays, ubodt = world
    synth = TraceSynthesizer(arrays, seed=9)
    pts = synth.synthesize(8, dt=60.0, uuid="s",
                           max_tries=300).trace["trace"]

    def run(matcher):
        out = []
        carry = None
        for p in pts:
            items = [{"points": [p], "carry": carry,
                      "t0": float(pts[0]["time"]), "pkey": ()}]
            (res, aux, carry) = matcher.match_sessions(items)[0]
            out.append((res[0].tolist(), res[1].tolist(), res[2].tolist()))
        return out, carry

    ref = SegmentMatcher(arrays=arrays, ubodt=ubodt, config=cfg)
    base, carry_b = run(ref)
    monkeypatch.setenv("REPORTER_SPARSE", "0")
    off = SegmentMatcher(
        arrays=arrays, ubodt=ubodt,
        config=dataclasses.replace(cfg, sparse=True, sparse_vmax_mps=16.0))
    got, carry_g = run(off)
    assert got == base
    for k in ("scores", "edge", "offset"):
        assert np.array_equal(carry_b[k], carry_g[k]), k


def test_session_sparse_engages(world):
    """A sparse-gap stream dispatches the sparse_session kind and its
    decode differs from the dense model where the model matters; a dense
    stream through the same matcher is bit-identical to the classic
    path."""
    cfg, arrays, ubodt = world
    synth = TraceSynthesizer(arrays, seed=10)
    sp_pts = synth.synthesize(8, dt=60.0, uuid="sp",
                              max_tries=300).trace["trace"]
    de_pts = synth.synthesize(8, dt=5.0, uuid="de",
                              max_tries=60).trace["trace"]
    cfg_sp = dataclasses.replace(cfg, sparse=True, sparse_vmax_mps=12.0,
                                 sparse_beta_scale=1.0)
    on = SegmentMatcher(arrays=arrays, ubodt=ubodt, config=cfg_sp)
    ref = SegmentMatcher(arrays=arrays, ubodt=ubodt, config=cfg)

    def step_all(matcher, pts):
        carry = None
        outs = []
        for p in pts:
            (res, _aux, carry) = matcher.match_sessions(
                [{"points": [p], "carry": carry,
                  "t0": float(pts[0]["time"]), "pkey": ()}])[0]
            outs.append([a.tolist() for a in res])
        return outs

    assert step_all(on, de_pts) == step_all(ref, de_pts)
    # the sparse stream engaged the sparse kind (dispatch counter moved)
    before = sparse_mod.C_SPARSE_DISPATCH.labels("ge60").value
    step_all(on, sp_pts)
    assert sparse_mod.C_SPARSE_DISPATCH.labels("ge60").value > before


# -- 2. the model -------------------------------------------------------------

def test_time_adaptive_beta_family():
    import jax.numpy as jnp

    from reporter_tpu.ops.viterbi import (
        MatchParams, SparseParams, sparse_beta, sparse_breakage,
    )

    p = MatchParams.from_config(MatcherConfig())
    sp = SparseParams.from_values(15.0, 1.0, 4.0, 34.0, 20.0, 3.0)
    b0 = float(sparse_beta(p, sp, jnp.float32(5.0)))
    b15 = float(sparse_beta(p, sp, jnp.float32(15.0)))
    b60 = float(sparse_beta(p, sp, jnp.float32(60.0)))
    b600 = float(sparse_beta(p, sp, jnp.float32(600.0)))
    assert b0 == b15 == pytest.approx(float(p.beta))  # at/below ref: base
    assert b60 > b15  # grows with the gap
    assert b600 == pytest.approx(float(p.beta) * 4.0)  # capped
    # breakage: fixed rule below, speed-conditioned above
    assert float(sparse_breakage(p, sp, jnp.float32(10.0))) == pytest.approx(
        float(p.breakage_distance))
    assert float(sparse_breakage(p, sp, jnp.float32(90.0))) == pytest.approx(
        34.0 * 90.0)
    assert float(sparse_breakage(p, None, jnp.float32(90.0))) == \
        pytest.approx(float(p.breakage_distance))


def test_gap_conditioned_breakage_connects():
    """An honest long-gap hop beyond the fixed breakage distance stays
    connected under the sparse model and restarts under the dense rule —
    pinned at the kernel level on a long-row grid where a 90 s drive
    really covers > breakage_distance metres."""
    import jax.numpy as jnp

    from reporter_tpu.ops import viterbi as V
    from reporter_tpu.synth.generator import dryrun_scenario

    # a 2 x 16 grid @ 200 m: one straight 3 km road; breakage shrunk so
    # the hop exceeds it while staying inside the UBODT delta
    cfg, arrays, ubodt = dryrun_scenario(rows=2, cols=16, spacing_m=200.0,
                                         delta=3000.0)
    cfg = dataclasses.replace(cfg, breakage_distance=800.0)
    dg = arrays.to_device()
    du = ubodt.to_device()
    p = V.MatchParams.from_config(cfg)
    sp = V.SparseParams.from_values(15.0, 0.0, 8.0, 34.0, 45.0, 0.0)
    brk_dense = V.sparse_breakage(p, None, jnp.float32(90.0))
    brk_sparse = V.sparse_breakage(p, sp, jnp.float32(90.0))
    assert float(brk_dense) == pytest.approx(800.0)
    assert float(brk_sparse) == pytest.approx(34.0 * 90.0)
    # two points 1200 m apart along the straight road, 90 s apart:
    # gc > 800 (dense restarts) but < 3060 (sparse connects)
    n0 = float(arrays.node_x[0]), float(arrays.node_y[0])
    px = np.array([[n0[0] + 10.0, n0[0] + 1210.0]], np.float32)
    py = np.array([[n0[1], n0[1]]], np.float32)
    tm = np.array([[0.0, 90.0]], np.float32)
    valid = np.ones((1, 2), bool)
    xin = V.pack_inputs(px, py, tm, valid)
    out_d = V.unpack_compact(V.match_batch_compact_packed(
        dg, du, xin, p, cfg.beam_k))
    out_s, _aux = V.match_batch_compact_packed_sparse(
        dg, du, xin, p, sp, cfg.beam_k)
    out_s = V.unpack_compact(out_s)
    assert bool(out_d[2][0, 1]) is True  # dense: the hop restarts the HMM
    assert bool(out_s[2][0, 1]) is False  # sparse: honest drive, connected


def test_sparse_agreement_improves_vs_oracle(world):
    """The headline: on a 60-90 s corpus, the calibrated sparse model
    agrees with its f64 oracle twin better than the dense model agrees
    with its own — the implementation-robustness the calibration sweep
    optimises (tools/calibrate.py; the committed CALIBRATION.json and
    QUALITY_BASELINE.json carry the full-size result)."""
    cfg, arrays, ubodt = world
    from reporter_tpu.baseline.brute_matcher import BruteForceMatcher

    synth = TraceSynthesizer(arrays, seed=21)
    traces = [synth.synthesize(16, dt=90.0, uuid="a%d" % i,
                               max_tries=400).trace for i in range(6)]

    def agreement(matcher, oracle):
        matcher._quality_aux = True
        agree = total = 0
        for tr in traces:
            m = matcher.match_many([tr])[0]
            edges = m["_quality"]["edge"]
            pts = tr["trace"]
            lats = np.array([p["lat"] for p in pts])
            lons = np.array([p["lon"] for p in pts])
            xs, ys = arrays.proj.to_xy(lats, lons)
            oe, _oo, _ob = oracle.match_points(
                xs, ys, [p["time"] for p in pts])
            seg_m = np.where(np.asarray(edges) >= 0,
                             arrays.edge_seg[np.maximum(edges, 0)], -1)
            seg_o = np.where(oe >= 0,
                             arrays.edge_seg[np.maximum(oe, 0)], -1)
            agree += int((seg_m == seg_o).sum())
            total += len(edges)
        return agree / total

    base = agreement(
        SegmentMatcher(arrays=arrays, ubodt=ubodt, config=cfg),
        BruteForceMatcher(arrays, cfg))
    vals = {"sigma_z": cfg.sigma_z, "beta": cfg.beta,
            "search_radius": cfg.search_radius, "k": cfg.beam_k,
            "beta_ref_s": 15.0, "beta_scale": 0.0, "beta_max": 8.0,
            "break_speed_mps": 34.0, "vmax_mps": 16.0, "plaus_weight": 3.0}
    cfg_sp = dataclasses.replace(
        cfg, sparse=True, sparse_vmax_mps=16.0, sparse_beta_scale=0.0)
    calibrated = agreement(
        SegmentMatcher(arrays=arrays, ubodt=ubodt, config=cfg_sp),
        BruteForceMatcher(arrays, cfg, sparse=vals))
    assert calibrated >= base, (calibrated, base)


# -- 3. the plane -------------------------------------------------------------

def test_calibration_load(world, tmp_path, monkeypatch):
    cfg, arrays, ubodt = world
    cal = {"version": 1, "cohorts": {
        "45-60": {"sigma_z": 5.0, "k": 12, "vmax_mps": 18.0},
        "ge60": {"beta_scale": 0.5, "vmax_mps": 14.0},
    }}
    path = tmp_path / "cal.json"
    path.write_text(json.dumps(cal))
    monkeypatch.setenv("REPORTER_CALIBRATION", str(path))
    m = SegmentMatcher(arrays=arrays, ubodt=ubodt,
                       config=dataclasses.replace(cfg, sparse=True))
    assert m.sparse.calibration is not None
    p, sp, k = m.sparse.params_for("45-60")
    assert float(p.sigma_z) == pytest.approx(5.0)
    assert k == 12
    assert float(sp.vmax) == pytest.approx(18.0)
    p2, sp2, k2 = m.sparse.params_for("ge60")
    assert float(sp2.beta_scale) == pytest.approx(0.5)
    assert float(sp2.vmax) == pytest.approx(14.0)
    assert k2 == cfg.sparse_beam_k  # unlisted keys: config family
    # per-request overrides win over calibration (reference precedence)
    p3, _sp3, _k3 = m.sparse.params_for("ge60", (9.0, 4.0, 30.0))
    assert float(p3.sigma_z) == pytest.approx(9.0)
    assert float(p3.search_radius) == pytest.approx(30.0)
    # the gauge says calibrated
    from reporter_tpu.matching.sparse import G_CALIBRATED

    assert G_CALIBRATED.value == 1.0


def test_calibration_corrupt_degrades(world, tmp_path, monkeypatch):
    cfg, arrays, ubodt = world
    path = tmp_path / "bad.json"
    path.write_text("{not json")
    monkeypatch.setenv("REPORTER_CALIBRATION", str(path))
    m = SegmentMatcher(arrays=arrays, ubodt=ubodt,
                       config=dataclasses.replace(cfg, sparse=True))
    assert m.sparse.enabled and m.sparse.calibration is None
    _p, sp, _k = m.sparse.params_for("ge60")
    assert float(sp.vmax) == pytest.approx(cfg.sparse_vmax_mps)


def test_radius_clamp_counted(world):
    cfg, arrays, ubodt = world
    from reporter_tpu.matching.sparse import C_RADIUS_CLAMPED

    m = SegmentMatcher(arrays=arrays, ubodt=ubodt, config=cfg)
    before = C_RADIUS_CLAMPED.labels("request").value
    eff = m.effective_match_options({"search_radius": 5000.0})
    assert eff["search_radius"] == pytest.approx(arrays.cell_size / 2.0)
    assert eff.get("search_radius_clamped") is True
    assert C_RADIUS_CLAMPED.labels("request").value == before + 1
    # an in-bounds radius carries no flag and no count
    eff2 = m.effective_match_options({"search_radius": 10.0})
    assert "search_radius_clamped" not in eff2
    assert C_RADIUS_CLAMPED.labels("request").value == before + 1
    # a sparse-cohort radius clamps through the same seam
    cfg_sp = dataclasses.replace(cfg, sparse=True,
                                 sparse_search_radius=9999.0)
    m2 = SegmentMatcher(arrays=arrays, ubodt=ubodt, config=cfg_sp)
    vals = m2.sparse.cohort_values("ge60")
    assert vals["search_radius"] == pytest.approx(arrays.cell_size / 2.0)
    assert C_RADIUS_CLAMPED.labels("sparse").value > 0


def test_interpolation_speed_weighted(world):
    """Two edges at different speeds between two matched points: the
    interpolated boundary time splits by free-flow TIME share, the
    classic walk by distance share — and the record schema (keys,
    rounding) is identical."""
    cfg, arrays, ubodt = world
    from reporter_tpu.matching.segments import associate_segments
    from reporter_tpu.matching.sparse import associate_interpolated

    # find two consecutive edges with different speeds
    es = np.asarray(arrays.edge_speed)
    el = np.asarray(arrays.edge_len)
    pair = None
    for e1 in range(arrays.num_edges):
        for e2 in range(arrays.num_edges):
            if int(arrays.edge_to[e1]) == int(arrays.edge_from[e2]) \
                    and es[e1] != es[e2] and e1 != e2:
                pair = (e1, e2)
                break
        if pair:
            break
    assert pair, "grid has mixed speeds by construction"
    e1, e2 = pair
    t0, t1 = 1000.0, 1000.0 + 60.0
    mps = [
        {"edge": e1, "offset": 0.0, "time": t0, "break": True,
         "shape_index": 0},
        {"edge": e2, "offset": float(el[e2]), "time": t1, "break": False,
         "shape_index": 1},
    ]
    classic = associate_segments(arrays, ubodt, mps)
    interp = associate_interpolated(arrays, ubodt, mps)
    assert [sorted(r.keys()) for r in classic] == \
        [sorted(r.keys()) for r in interp]
    assert [type(v).__name__ for r in classic for v in r.values()] == \
        [type(v).__name__ for r in interp for v in r.values()]
    # boundary time between the two edges: classic = distance-linear,
    # interpolated = free-flow time share
    d1, d2 = float(el[e1]), float(el[e2])
    ff1 = d1 / max(float(es[e1]), 0.1)
    ff2 = d2 / max(float(es[e2]), 0.1)
    lin = t0 + 60.0 * d1 / (d1 + d2)
    spd = t0 + 60.0 * ff1 / (ff1 + ff2)
    assert lin != pytest.approx(spd)  # speeds differ so the shares differ

    def boundary_time(records):
        # end_time of the first fully-exited segment record
        for r in records:
            if r.get("end_time", -1) != -1:
                return r["end_time"]
        return None

    bt_classic = boundary_time(classic)
    bt_interp = boundary_time(interp)
    if bt_classic is not None and bt_interp is not None \
            and bt_classic not in (t0, t1):
        assert bt_interp == pytest.approx(spd, abs=0.51)
        assert bt_classic == pytest.approx(lin, abs=0.51)


def test_interpolate_match_option_end_to_end(world):
    """match_options.interpolate routes a trace's association through the
    engine; absent, bytes are the PR 14 walk."""
    cfg, arrays, ubodt = world
    traces = corpus(arrays, seed=6, dense_n=0, sparse_n=2)
    m = SegmentMatcher(arrays=arrays, ubodt=ubodt, config=cfg)
    base = m.match_many(traces)
    ti = [dict(t, match_options={"interpolate": True}) for t in traces]
    res = m.match_many(ti)
    # same segments traversed (the engine re-times, never re-routes)
    for b, r in zip(base, res):
        assert [s.get("segment_id") for s in b["segments"]] == \
            [s.get("segment_id") for s in r["segments"]]
    # explicit false == absent
    tf = [dict(t, match_options={"interpolate": False}) for t in traces]
    assert wire(m.match_many(tf)) == wire(base)
    # config default applies without per-request keys
    m2 = SegmentMatcher(arrays=arrays, ubodt=ubodt,
                        config=dataclasses.replace(cfg, interpolate=True))
    assert wire(m2.match_many(traces)) == wire(res)


def test_gap_jitter_corpus():
    """loadgen --gap-jitter: non-uniform realized gaps, recorded in the
    artifact histogram; jitter 0 keeps the seeded corpus identical."""
    import importlib.util
    import os as _os

    spec = importlib.util.spec_from_file_location(
        "loadgen", _os.path.join(_os.path.dirname(_os.path.dirname(
            _os.path.abspath(__file__))), "tools", "loadgen.py"))
    lg = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lg)

    plain_a = lg.synth_sessions(4, 16, 8, 6, seed=3, gaps=[60.0])
    plain_b = lg.synth_sessions(4, 16, 8, 6, seed=3, gaps=[60.0],
                                gap_jitter=0.0)
    assert json.dumps(plain_a) == json.dumps(plain_b)
    jit = lg.synth_sessions(4, 16, 8, 6, seed=3, gaps=[60.0],
                            gap_jitter=0.25)
    h = lg.realized_gaps(jit)
    assert h["count"] > 0
    assert h["max_s"] > h["min_s"] + 1.0, h  # genuinely non-uniform
    assert 45.0 <= h["median_s"] <= 75.0, h  # centred on the nominal gap
    h0 = lg.realized_gaps(plain_a)
    assert h0["max_s"] == pytest.approx(h0["min_s"])  # metronomic before


def test_quality_oracle_sparse_keying(world):
    """The shadow-oracle plane builds a sparse-model oracle for
    sparse-cohort traces (same model both sides — a model improvement
    must not score as a regression)."""
    cfg, arrays, ubodt = world
    from reporter_tpu.obs.quality import QualityEngine

    cfg_sp = dataclasses.replace(cfg, sparse=True, sparse_vmax_mps=16.0)
    m = SegmentMatcher(arrays=arrays, ubodt=ubodt, config=cfg_sp)
    eng = QualityEngine(m, sample_every=1, start_worker=False,
                        slo_feed=lambda v, w: None)
    tr = corpus(arrays, seed=7, dense_n=0, sparse_n=1)[0]
    m._quality_aux = True
    match = m.match_many([tr])[0]
    frac = eng.compare(tr, match["_quality"]["edge"])
    assert frac is not None
    # a sparse-cohort oracle was built, keyed by its gap label, and
    # carries the sparse model
    keys = list(eng._oracles)
    assert any(sl for _pk, sl in keys), keys
    oracle = next(v for (pk, sl), v in eng._oracles.items() if sl)
    assert oracle.sparse is not None
    assert oracle.sparse["vmax_mps"] == pytest.approx(16.0)
