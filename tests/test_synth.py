import numpy as np
import pytest

from reporter_tpu.matching import SegmentMatcher, MatcherConfig
from reporter_tpu.synth import TraceSynthesizer
from reporter_tpu.synth.generator import segment_agreement
from reporter_tpu.tiles.network import grid_city
from reporter_tpu.tiles.arrays import build_graph_arrays
from reporter_tpu.tiles.ubodt import build_ubodt


@pytest.fixture(scope="module")
def setup():
    city = grid_city(rows=6, cols=6, spacing_m=150.0)
    arrays = build_graph_arrays(city, cell_size=100.0)
    ubodt = build_ubodt(arrays, delta=2000.0)
    return arrays, ubodt


def test_route_is_connected(setup):
    arrays, _ = setup
    synth = TraceSynthesizer(arrays, seed=1)
    edges = synth.route(0, 35)
    assert edges
    assert int(arrays.edge_from[edges[0]]) == 0
    assert int(arrays.edge_to[edges[-1]]) == 35
    for a, b in zip(edges, edges[1:]):
        assert int(arrays.edge_to[a]) == int(arrays.edge_from[b])


def test_walk_positions_on_path(setup):
    arrays, _ = setup
    synth = TraceSynthesizer(arrays, seed=2)
    edges = synth.route(0, 11)
    xy, ts, eids = synth.walk(edges, dt=5.0)
    assert len(xy) == len(ts) == len(eids)
    # samples are spaced by speed * dt along the path
    assert (np.diff(ts) == 5.0).all()
    # every sample's claimed edge contains (approximately) the sample point
    from reporter_tpu import geo

    for (x, y), e in zip(xy, eids):
        x0, y0 = arrays.node_x[arrays.edge_from[e]], arrays.node_y[arrays.edge_from[e]]
        x1, y1 = arrays.node_x[arrays.edge_to[e]], arrays.node_y[arrays.edge_to[e]]
        d, _ = geo.point_segment_distance_np(x, y, x0, y0, x1, y1)
        assert d < 1.0


def test_synthesize_deterministic_shape(setup):
    arrays, _ = setup
    synth = TraceSynthesizer(arrays, seed=3)
    st = synth.synthesize(20, dt=10.0, sigma=4.0)
    assert len(st.trace["trace"]) == 20
    assert st.truth_edge.shape == (20,)
    assert st.trace["trace"][1]["time"] - st.trace["trace"][0]["time"] == 10.0


def test_matcher_recovers_truth(setup):
    """The end-to-end accuracy loop: synthesize noisy traces, match, compare
    segments to ground truth.  With 5 m noise on a 150 m grid the matcher
    should recover nearly all segments."""
    arrays, ubodt = setup
    matcher = SegmentMatcher(arrays=arrays, ubodt=ubodt, config=MatcherConfig())
    synth = TraceSynthesizer(arrays, seed=4)
    traces = synth.batch(4, 24, dt=10.0, sigma=4.0)
    results = matcher.match_many([t.trace for t in traces])

    # recompute matched edge per point via the raw kernel interface
    agreements = []
    for st in traces:
        # run single to get per-point edges (match_many returns segments; use
        # the internal batch runner for point-level truth comparison)
        pts = st.trace["trace"]
        lats = np.array([p["lat"] for p in pts])
        lons = np.array([p["lon"] for p in pts])
        x, y = arrays.proj.to_xy(lats, lons)
        px = x[None].astype(np.float32)
        py = y[None].astype(np.float32)
        tm = (np.array([p["time"] for p in pts]) - pts[0]["time"])[None].astype(np.float32)
        valid = np.ones_like(px, bool)
        edge, _, _ = matcher._run_batch(px, py, tm, valid)
        agreements.append(segment_agreement(arrays, edge[0], st))
    assert np.mean(agreements) > 0.9, agreements
