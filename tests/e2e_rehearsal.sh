#!/usr/bin/env bash
# Full-pipeline rehearsal, the tests/circle.sh equivalent (reference
# tests/circle.sh:1-113): boot the matching service, replay probe records
# through the stream runtime, and assert anonymised time-quantised tiles
# land in the results dir; then run the batch pipeline over the same records
# as an archive and assert its tiles too.  No Kafka/S3/docker needed -- the
# stream runtime reads stdin and the batch archive is a local dir (the
# transports are swappable; kafka_io adds the broker).
#
# Usage: tests/e2e_rehearsal.sh [workdir]
set -euo pipefail

# shared spawn/trap/cleanup/wait helpers (tests/rehearsal_lib.sh)
. "$(dirname "$0")/rehearsal_lib.sh"
reh_init "${1:-}" reporter-e2e
# the rehearsal service runs the SHARDED matcher (devices=2 in the config
# below) on a virtual 2-device CPU mesh — the integrated mesh path must
# survive the full pipeline, not just unit tests (VERDICT r03 next #4)
if [[ "${XLA_FLAGS:-}" != *xla_force_host_platform_device_count* ]]; then
    export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=2"
fi

PORT=18021
mkdir -p "$WORK/results" "$WORK/archive" "$WORK/batch_out"
echo "rehearsal workdir: $WORK"

# ---- config + synthetic probes -------------------------------------------
cat > "$WORK/config.json" <<EOF
{
  "network": {"type": "grid", "rows": 8, "cols": 8, "spacing_m": 200},
  "matcher": {"sigma_z": 4.07, "beta": 3.0, "search_radius": 50.0, "devices": 2},
  "backend": "jax",
  "batch": {"max_batch": 64, "max_wait_ms": 5}
}
EOF

python - "$WORK" <<'EOF'
# probes as sv rows "uuid|epoch|lat|lon|acc", one file per vehicle in the
# archive dir and one merged stream file
import os, sys
from reporter_tpu.utils.jaxenv import ensure_platform
ensure_platform()
from reporter_tpu.synth import TraceSynthesizer
from reporter_tpu.tiles.arrays import build_graph_arrays
from reporter_tpu.tiles.network import grid_city

work = sys.argv[1]
city = grid_city(rows=8, cols=8, spacing_m=200.0)  # == service config
arrays = build_graph_arrays(city, cell_size=100.0)
synth = TraceSynthesizer(arrays, seed=42)
rows = []
for i, s in enumerate(synth.batch(12, 30, dt=5.0, sigma=5.0)):
    lines = [
        "veh-%02d|%d|%.7f|%.7f|5" % (i, p["time"], p["lat"], p["lon"])
        for p in s.trace["trace"]
    ]
    with open(os.path.join(work, "archive", "part-%02d.csv" % i), "w") as f:
        f.write("\n".join(lines) + "\n")
    rows.extend(lines)
with open(os.path.join(work, "stream.sv"), "w") as f:
    f.write("\n".join(rows) + "\n")
print("wrote %d probe rows" % len(rows))
EOF

# ---- boot the matching service -------------------------------------------
python -m reporter_tpu.serve "$WORK/config.json" "127.0.0.1:$PORT" \
    > "$WORK/serve.log" 2>&1 &
SERVE_PID=$!
# cleanup on EVERY exit path, with SIGKILL escalation, via the shared
# lib trap: a failed leg must not strand the listener to poison later
# CI legs on the same runner
reh_track "$SERVE_PID"

# the socket binds before the engine builds (deferred boot): readiness
# is /health reporting an attached engine (backend non-null) — NOT
# warming false, which would also gate on the full shape-compile set
if ! reh_wait_replica "http://127.0.0.1:$PORT" 120; then
    echo "FAIL: service never started; tail of serve.log:"
    tail -20 "$WORK/serve.log"
    exit 1
fi
echo "service up (pid $SERVE_PID)"

# ---- streaming path: stdin -> windows -> /report -> anonymised tiles -----
python -m reporter_tpu.stream \
    --format ',sv,\|,0,2,3,1,4' \
    --reporter-url "http://127.0.0.1:$PORT/report" \
    --privacy 1 --quantisation 3600 --flush-interval 5 \
    --source RHRSL --output "$WORK/results" \
    < "$WORK/stream.sv"

TILES=$(find "$WORK/results" -type f | wc -l)
echo "stream tiles written: $TILES"
test "$TILES" -ge 1 || { echo "FAIL: no stream tiles"; exit 1; }
for f in $(find "$WORK/results" -type f); do
    test -s "$f" || { echo "FAIL: empty tile $f"; exit 1; }
done

# ---- batch path: archive dir -> 3 resumable phases -> tiles --------------
python -m reporter_tpu.batch \
    --src "$WORK/archive" \
    --src-valuer 'lambda l: (lambda c: (c[0], c[1], c[2], c[3], c[4]))(l.split("|"))' \
    --src-time-pattern '' \
    --match-config "$WORK/config.json" \
    --dest "dir:$WORK/batch_out" \
    --privacy 1 --quantisation 3600 --source-id RHRSL \
    --concurrency 1

BTILES=$(find "$WORK/batch_out" -type f | wc -l)
echo "batch tiles written: $BTILES"
test "$BTILES" -ge 1 || { echo "FAIL: no batch tiles"; exit 1; }

echo "e2e rehearsal OK (stream: $TILES tiles, batch: $BTILES tiles)"
