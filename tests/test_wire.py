"""Binary columnar wire codec (serve/wire.py): round-trip fuzz + the
JSON-vs-binary service differential.

The codec's contract is DICT-IDENTITY: decode(encode(body)) must equal the
JSON body exactly — same values, same int/float typing — with unknown keys
riding the JSON tail, so ``json.dumps(..., sort_keys=True)`` equality is
the assertion everywhere.  Malformed/truncated frames must raise WireError
and never over-read.  The service half: a binary request against the live
HTTP service must produce the byte-for-byte same payload as its JSON twin,
under every negotiation combination (binary-in/JSON-out, JSON-in/
binary-out, gzip), with /health advertising the capability and
REPORTER_WIRE=0 turning the whole plane off.
"""

import gzip
import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from reporter_tpu.matching import MatcherConfig, SegmentMatcher
from reporter_tpu.serve import ReporterService, wire
from reporter_tpu.tiles.arrays import build_graph_arrays
from reporter_tpu.tiles.network import grid_city
from reporter_tpu.tiles.ubodt import build_ubodt


def _strip(body):
    """Drop the decode's ``_columns`` transport side channel."""
    if isinstance(body, dict) and "traces" in body:
        for tr in body["traces"]:
            tr.pop("_columns", None)
    elif isinstance(body, dict):
        body.pop("_columns", None)
    return body


def _jeq(a, b):
    assert json.dumps(a, sort_keys=True) == json.dumps(b, sort_keys=True)


def _random_trace(rng, i, n_pts):
    pts = []
    for j in range(n_pts):
        lat = float(rng.uniform(-90, 90))
        lon = float(rng.uniform(-180, 180))
        t = 1_460_000_000 + 15 * j
        mode = rng.integers(0, 3)
        if mode == 0:       # all-float columns
            t = float(t) + float(rng.uniform(0, 1))
        elif mode == 1:     # all-int lat/lon/time
            lat, lon = int(lat), int(lon)
        # mode 2: mixed — leave lat/lon float, time int
        p = {"lat": lat, "lon": lon, "time": t}
        if rng.integers(0, 2):
            p["accuracy"] = int(rng.integers(1, 30))
        pts.append(p)
    tr = {"trace": pts}
    if rng.integers(0, 4):
        tr["uuid"] = "véh-Ω-%d" % i        # unicode uuids must survive
    if rng.integers(0, 2):
        tr["match_options"] = {"mode": "auto", "report_levels": [0, 1]}
    if rng.integers(0, 3) == 0:
        tr["stream"] = True
    if rng.integers(0, 4) == 0:
        del tr["trace"]                    # absent-key traces round-trip
    return tr


class TestRequestCodec:
    def test_round_trip_fuzz(self):
        rng = np.random.default_rng(42)
        for _ in range(40):
            body = {"traces": [
                _random_trace(rng, i, int(rng.integers(0, 12)))
                for i in range(int(rng.integers(0, 6)))]}
            if rng.integers(0, 2):
                body["mode"] = "auto"       # body-level extras
            buf = wire.encode_request(body)
            _jeq(_strip(wire.decode_request(buf)), body)

    def test_single_trace_flag(self):
        tr = {"uuid": "v", "trace": [
            {"lat": 1.5, "lon": 2.5, "time": 1000}]}
        buf = wire.encode_request(tr)
        out = wire.decode_request(buf)
        assert "traces" not in out
        _jeq(_strip(out), tr)

    def test_int_float_typing_exact(self):
        tr = {"trace": [{"lat": 1, "lon": 2.0, "time": 10},
                        {"lat": 3.5, "lon": 4, "time": 20.5}]}
        pts = wire.decode_request(wire.encode_request(tr))["trace"]
        assert isinstance(pts[0]["lat"], int) and isinstance(
            pts[0]["lon"], float) and isinstance(pts[0]["time"], int)
        assert isinstance(pts[1]["lat"], float) and isinstance(
            pts[1]["lon"], int) and isinstance(pts[1]["time"], float)

    def test_accuracy_column_typing_and_irregularity(self):
        """Uniform per-point accuracy rides the fourth f64 column with
        exact int/float typing; irregular presence (or non-numeric
        values) falls back to the extras tail — both round-trip."""
        uniform = {"trace": [
            {"lat": 1.0, "lon": 2.0, "time": 10, "accuracy": 5},
            {"lat": 1.5, "lon": 2.5, "time": 20, "accuracy": 7.5}]}
        pts = wire.decode_request(wire.encode_request(uniform))["trace"]
        assert isinstance(pts[0]["accuracy"], int)
        assert isinstance(pts[1]["accuracy"], float)
        for irregular in (
                {"trace": [{"lat": 1.0, "lon": 2.0, "time": 10,
                            "accuracy": 5},
                           {"lat": 1.5, "lon": 2.5, "time": 20}]},
                {"trace": [{"lat": 1.0, "lon": 2.0, "time": 10,
                            "accuracy": "gps"}]},
                {"trace": [{"lat": 1.0, "lon": 2.0, "time": 10,
                            "accuracy": True}]},
                {"trace": [{"lat": 1.0, "lon": 2.0, "time": 10,
                            "accuracy": 1 << 60}]}):
            out = _strip(wire.decode_request(wire.encode_request(irregular)))
            _jeq(out, irregular)
        # uniform accuracy must be cheaper on the wire than tail spill
        many = {"trace": [{"lat": 1.0, "lon": 2.0, "time": 10 + i,
                           "accuracy": 5} for i in range(64)]}
        spilly = {"trace": [dict(p, accuracy="5") for p in many["trace"]]}
        assert len(wire.encode_request(many)) < len(
            wire.encode_request(spilly))

    def test_rejects_uncarriable_bodies(self):
        bad = [
            {"trace": [{"lat": "x", "lon": 0, "time": 0}]},
            {"trace": [{"lat": True, "lon": 0, "time": 0}]},
            {"trace": [{"lat": 0, "lon": 0}]},                 # missing time
            {"trace": [{"lat": 0, "lon": 0, "time": 1 << 53}]},
            {"traces": "nope"},
            {"trace": "nope"},
        ]
        for body in bad:
            with pytest.raises(wire.WireError):
                wire.encode_request(body)

    def test_columns_side_channel(self):
        tr = {"trace": [{"lat": 1.25, "lon": -2.5, "time": 100},
                        {"lat": 3.0, "lon": 4.0, "time": 115}]}
        out = wire.decode_request(wire.encode_request(tr))
        c = out["_columns"]
        assert c["lat"].dtype == np.float64
        assert c["lat"].tolist() == [1.25, 3.0]
        assert c["time"].tolist() == [100.0, 115.0]

    def test_sniff_request(self):
        body = {"traces": [
            {"uuid": "a", "stream": True,
             "trace": [{"lat": 10.5, "lon": -20.5, "time": 1}]},
            {"uuid": "b", "trace": []},
            {"trace": [{"lat": 1.0, "lon": 2.0, "time": 3}]},
        ]}
        sniff = wire.sniff_request(wire.encode_request(body))
        assert sniff[0] == {"uuid": "a", "stream": True,
                            "lat": 10.5, "lon": -20.5}
        assert sniff[1]["uuid"] == "b" and sniff[1]["lat"] is None
        assert sniff[2]["uuid"] is None and not sniff[2]["stream"]


def _result(i, n_segs=3, n_reps=2):
    segs = []
    for s in range(n_segs):
        segs.append({
            "way_ids": [100 + s], "internal": bool(s % 2),
            "queue_length": 0, "begin_shape_index": s,
            "end_shape_index": s + 1,
            "segment_id": 7000 + s if s else -1,
            "start_time": -1 if s == 0 else round(1000.0 + s, 2),
            "end_time": round(1001.0 + s, 2), "length": -1 if s == 0 else 150.0,
        })
    reps = [{"id": 7000 + r, "t0": 1000.0 + r, "t1": 1001.0 + r,
             "length": 150.0, "queue_length": 0} for r in range(n_reps)]
    if reps:
        reps[0]["next_id"] = 7001
        reps[0]["huge"] = 1 << 60           # spills to the tail exactly
    return {"segment_matcher": {"segments": segs, "mode": "auto"},
            "datastore": {"reports": reps, "mode": "auto"},
            "stats": {"i": i}}


class TestResponseCodec:
    def test_batch_round_trip(self):
        payload = {"results": [_result(0), _result(1, 0, 0),
                               {"error": "trace too short"},  # raw rest path
                               _result(3, 5, 1)],
                   "units": "km"}
        _jeq(wire.decode_response(wire.encode_response(payload)), payload)

    def test_single_round_trip(self):
        payload = _result(0)
        buf = wire.encode_response(payload, single=True)
        out = wire.decode_response(buf)
        assert "results" not in out
        _jeq(out, payload)

    def test_degraded_flag_peek(self):
        p = {"results": [_result(0)], "degraded": True}
        buf = wire.encode_response(p)
        assert wire.response_degraded(buf)
        _jeq(wire.decode_response(buf), p)
        assert not wire.response_degraded(
            wire.encode_response({"results": []}))
        assert not wire.response_degraded(b"RPTCgarbage")
        assert not wire.response_degraded(b"")

    def test_unknown_keys_round_trip(self):
        """Schema growth must not need a wire version bump: unknown
        segment/report/result keys ride the tail."""
        res = _result(0)
        res["segment_matcher"]["segments"][0]["new_field"] = [1, {"a": 2}]
        res["datastore"]["reports"][0]["confidence"] = 0.75
        res["future_block"] = {"x": None}
        payload = {"results": [res]}
        _jeq(wire.decode_response(wire.encode_response(payload)), payload)


class TestMalformedFrames:
    def test_truncation_never_overreads(self):
        rng = np.random.default_rng(7)
        req = wire.encode_request({"traces": [
            _random_trace(rng, i, 6) for i in range(3)]})
        resp = wire.encode_response({"results": [_result(0), _result(1)]})
        for buf, dec in ((req, wire.decode_request),
                         (resp, wire.decode_response)):
            for cut in range(0, len(buf) - 1, 3):
                with pytest.raises(wire.WireError):
                    dec(buf[:cut])

    def test_header_validation(self):
        req = wire.encode_request({"traces": []})
        with pytest.raises(wire.WireError):
            wire.decode_request(b"XXXX" + req[4:])     # bad magic
        with pytest.raises(wire.WireError):
            wire.decode_request(req[:4] + b"\x09" + req[5:])  # bad version
        with pytest.raises(wire.WireError):
            wire.decode_request(wire.encode_response({"results": []}))
        with pytest.raises(wire.WireError):
            wire.decode_response(req)                  # kind mismatch

    def test_lying_interior_lengths(self):
        """A frame whose length fields point past the buffer must raise,
        not over-read (every count is bounds-checked)."""
        import struct

        buf = bytearray(wire.encode_request(
            {"traces": [{"trace": [{"lat": 1.0, "lon": 2.0, "time": 3}]}]}))
        struct.pack_into("<I", buf, 8, 0xFFFFFF)       # n_traces lie
        with pytest.raises(wire.WireError):
            wire.decode_request(bytes(buf))

    def test_is_wire(self):
        assert wire.is_wire("application/x-reporter-columnar")
        assert wire.is_wire("application/x-reporter-columnar; charset=x")
        assert not wire.is_wire("application/json")
        assert not wire.is_wire(None)
        assert not wire.is_wire("")


# -- live-service differential ----------------------------------------------


@pytest.fixture(scope="module")
def served():
    city = grid_city(rows=5, cols=5, spacing_m=150.0)
    arrays = build_graph_arrays(city, cell_size=100.0)
    ubodt = build_ubodt(arrays, delta=2000.0)
    matcher = SegmentMatcher(arrays=arrays, ubodt=ubodt,
                             config=MatcherConfig())
    service = ReporterService(matcher, max_wait_ms=5.0)
    httpd = service.make_server("127.0.0.1", 0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    url = "http://127.0.0.1:%d" % httpd.server_port
    yield url, arrays, service
    httpd.shutdown()


def _post(url, data, headers):
    req = urllib.request.Request(url, data=data, headers=headers)
    try:
        with urllib.request.urlopen(req, timeout=60) as r:
            return r.status, dict(r.headers), r.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


def _street_trace(arrays, row=2, n=10, uuid="veh-w"):
    nodes = [row * 5 + c for c in range(5)]
    t = np.linspace(0.05, 0.9, n)
    xs = np.interp(t, np.linspace(0, 1, 5), arrays.node_x[nodes])
    ys = np.interp(t, np.linspace(0, 1, 5), arrays.node_y[nodes])
    lat, lon = arrays.proj.to_latlon(xs, ys)
    return {"uuid": uuid,
            "trace": [{"lat": float(a), "lon": float(o), "time": 1000 + 15 * i}
                      for i, (a, o) in enumerate(zip(lat, lon))],
            "match_options": {"mode": "auto", "report_levels": [0, 1, 2],
                              "transition_levels": [0, 1, 2]}}


JSON_H = {"Content-Type": "application/json"}
BIN_H = {"Content-Type": wire.CONTENT_TYPE, "Accept": wire.CONTENT_TYPE}


class TestServiceDifferential:
    def test_batch_json_vs_binary(self, served):
        url, arrays, _ = served
        body = {"traces": [_street_trace(arrays, row=r, uuid="veh-%d" % r)
                           for r in (1, 2, 3)]}
        _, _, jraw = _post(url + "/trace_attributes_batch",
                           json.dumps(body).encode(), JSON_H)
        code, hdrs, braw = _post(url + "/trace_attributes_batch",
                                 wire.encode_request(body), BIN_H)
        assert code == 200
        assert wire.is_wire(hdrs.get("Content-Type"))
        assert len(braw) < len(jraw)   # the point of the exercise
        _jeq(wire.decode_response(braw), json.loads(jraw))

    def test_single_report_json_vs_binary(self, served):
        url, arrays, _ = served
        tr = _street_trace(arrays)
        _, _, jraw = _post(url + "/report", json.dumps(tr).encode(), JSON_H)
        code, hdrs, braw = _post(url + "/report",
                                 wire.encode_request(tr), BIN_H)
        assert code == 200 and wire.is_wire(hdrs.get("Content-Type"))
        _jeq(wire.decode_response(braw), json.loads(jraw))

    def test_binary_in_json_out(self, served):
        url, arrays, _ = served
        tr = _street_trace(arrays)
        code, hdrs, raw = _post(url + "/report", wire.encode_request(tr),
                                {"Content-Type": wire.CONTENT_TYPE})
        assert code == 200 and not wire.is_wire(hdrs.get("Content-Type"))
        _, _, jraw = _post(url + "/report", json.dumps(tr).encode(), JSON_H)
        _jeq(json.loads(raw), json.loads(jraw))

    def test_gzip_request(self, served):
        url, arrays, _ = served
        tr = _street_trace(arrays)
        code, _, raw = _post(
            url + "/report", gzip.compress(json.dumps(tr).encode()),
            {"Content-Type": "application/json",
             "Content-Encoding": "gzip"})
        assert code == 200
        _, _, jraw = _post(url + "/report", json.dumps(tr).encode(), JSON_H)
        _jeq(json.loads(raw), json.loads(jraw))

    def test_health_advertises_capabilities(self, served):
        url, _, service = served
        with urllib.request.urlopen(url + "/health", timeout=30) as r:
            h = json.loads(r.read())
        assert "gzip" in h["capabilities"]
        assert ("wire-columnar" in h["capabilities"]) == service.wire_enabled

    def test_bad_gzip_is_400(self, served):
        url, _, _ = served
        code, _, raw = _post(
            url + "/report", b"\x1f\x8bnot-gzip-at-all",
            {"Content-Type": "application/json",
             "Content-Encoding": "gzip"})
        assert code == 400 and b"error" in raw

    def test_unknown_content_encoding_is_415(self, served):
        url, arrays, _ = served
        code, _, _ = _post(
            url + "/report", json.dumps(_street_trace(arrays)).encode(),
            {"Content-Type": "application/json", "Content-Encoding": "br"})
        assert code == 415

    def test_garbage_binary_frame_is_400(self, served):
        url, _, _ = served
        code, _, _ = _post(url + "/report", b"RPTC\x01\x01\x00\x00junk",
                           {"Content-Type": wire.CONTENT_TYPE})
        assert code == 400

    def test_wire_disabled_rejects_binary(self, served):
        url, arrays, service = served
        tr = _street_trace(arrays)
        service.wire_enabled = False
        try:
            code, _, _ = _post(url + "/report", wire.encode_request(tr),
                               BIN_H)
            assert code == 415
            # and the capability disappears from /health
            with urllib.request.urlopen(url + "/health", timeout=30) as r:
                h = json.loads(r.read())
            assert "wire-columnar" not in h["capabilities"]
            # Accept alone must not produce a binary response either
            code, hdrs, _ = _post(url + "/report",
                                  json.dumps(tr).encode(),
                                  dict(JSON_H, Accept=wire.CONTENT_TYPE))
            assert code == 200 and not wire.is_wire(hdrs.get("Content-Type"))
        finally:
            service.wire_enabled = True


def test_cli_env_defaults_restored(tmp_path, monkeypatch):
    """The serve entrypoint's REPORTER_WIRE / REPORTER_HOST_PACK
    setdefaults must not outlive main(): an in-process CLI caller would
    otherwise leak serving defaults into library-default code."""
    import reporter_tpu.serve.__main__ as cli

    for k in ("REPORTER_WIRE", "REPORTER_HOST_PACK"):
        monkeypatch.delenv(k, raising=False)
    conf = tmp_path / "conf.json"
    conf.write_text(json.dumps({
        "network": {"type": "file", "path": str(tmp_path / "missing.json")},
        "warmup": False,
    }))
    rc = cli.main(["serve", str(conf), "127.0.0.1:0"])
    assert rc == 1
    import os
    assert "REPORTER_WIRE" not in os.environ
    assert "REPORTER_HOST_PACK" not in os.environ
    # an EXPLICIT env value is the operator's, not the default's: it stays
    monkeypatch.setenv("REPORTER_WIRE", "0")
    cli.main(["serve", str(conf), "127.0.0.1:0"])
    assert os.environ["REPORTER_WIRE"] == "0"
