"""Multi-chip sharding tests on the virtual 8-device CPU mesh."""

import numpy as np
import pytest

from reporter_tpu.matching.config import MatcherConfig
from reporter_tpu.tiles.network import grid_city
from reporter_tpu.tiles.arrays import build_graph_arrays
from reporter_tpu.tiles.ubodt import build_ubodt

K = 8


@pytest.fixture(scope="module")
def setup():
    city = grid_city(rows=5, cols=5, spacing_m=150.0)
    arrays = build_graph_arrays(city, cell_size=100.0)
    ubodt = build_ubodt(arrays, delta=2000.0)
    return arrays, ubodt


def make_batch(arrays, B=8, T=12, seed=3):
    from reporter_tpu.synth.generator import example_grid_batch

    return example_grid_batch(arrays, B, T, seed)


def test_eight_device_mesh_available():
    import jax

    assert len(jax.devices()) == 8, "conftest must provide 8 virtual CPU devices"


def test_sharded_matches_unsharded(setup):
    import jax
    import jax.numpy as jnp

    from reporter_tpu.ops.viterbi import MatchParams, match_batch
    from reporter_tpu.parallel import make_mesh, sharded_match_fn, match_and_histogram

    arrays, ubodt = setup
    dg, du = arrays.to_device(), ubodt.to_device()
    p = MatchParams.from_config(MatcherConfig())
    px, py, times, valid = make_batch(arrays)
    S = len(arrays.seg_ids)

    mesh = make_mesh()
    fn = sharded_match_fn(mesh, K, S)
    res_sh, hist_sh = fn(dg, du, jnp.asarray(px), jnp.asarray(py), jnp.asarray(times), jnp.asarray(valid), p)

    res_1, hist_1 = match_and_histogram(
        dg, du, jnp.asarray(px), jnp.asarray(py), jnp.asarray(times), jnp.asarray(valid), p, K, S
    )
    np.testing.assert_array_equal(np.asarray(res_sh.idx), np.asarray(res_1.idx))
    np.testing.assert_allclose(np.asarray(hist_sh.point_count), np.asarray(hist_1.point_count), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(hist_sh.time_in_segment), np.asarray(hist_1.time_in_segment), rtol=1e-5)

    # all points matched -> histogram accounts for every (valid) point
    assert float(np.asarray(hist_sh.point_count).sum()) == px.size


def test_histogram_semantics(setup):
    import jax.numpy as jnp

    from reporter_tpu.ops.viterbi import MatchParams
    from reporter_tpu.parallel import match_and_histogram

    arrays, ubodt = setup
    dg, du = arrays.to_device(), ubodt.to_device()
    p = MatchParams.from_config(MatcherConfig())
    # one trace driving one street: dwell time in each visited segment sums to
    # roughly the trace duration
    px, py, times, valid = make_batch(arrays, B=1, T=10, seed=5)
    S = len(arrays.seg_ids)
    _, hist = match_and_histogram(
        dg, du, jnp.asarray(px), jnp.asarray(py), jnp.asarray(times), jnp.asarray(valid), p, K, S
    )
    total_time = float(np.asarray(hist.time_in_segment).sum())
    assert 0 < total_time <= (10 - 1) * 15.0 + 1e-3
    # trace_count is exact per (trace, segment): one straight drive touches
    # each visited segment once, so no count can exceed the number of traces
    tc = np.asarray(hist.trace_count)
    assert tc.max() == 1.0 and tc.sum() >= 1.0


def _has_shard_map() -> bool:
    # the parallel.rules shim bridges jax.shard_map (new builds) and
    # jax.experimental.shard_map (0.4.x) — only a build with NEITHER skips
    try:
        from reporter_tpu.parallel.rules import shard_map  # noqa: F401

        return True
    except Exception:  # noqa: BLE001 - capability probe
        return False


@pytest.mark.skipif(not _has_shard_map(),
                    reason="this jax build lacks shard_map entirely")
@pytest.mark.parametrize("layout", ["cuckoo", "wide32"])
def test_graph_sharded_matches_unsharded(setup, layout):
    """UBODT sharded over gp: decode and histogram must agree with the
    single-device path (probes resolve exactly via pmin/pmax) — for both
    table layouts (the wide32 sharded probe masks ONE bucket range per
    rank instead of two)."""
    import jax
    import jax.numpy as jnp

    from reporter_tpu.ops.viterbi import MatchParams
    from reporter_tpu.parallel import (
        graph_sharded_match_fn,
        make_mesh2,
        match_and_histogram,
        check_ubodt_shardable,
    )

    arrays, ubodt = setup
    ubodt = ubodt.relayout(layout)
    cfg = MatcherConfig()
    p = MatchParams.from_config(cfg)
    dg = arrays.to_device()
    du = check_ubodt_shardable(ubodt, 4).to_device()
    S = len(arrays.seg_ids)

    px, py, times, valid = make_batch(arrays, B=8, T=12)
    args = tuple(jnp.asarray(a) for a in (px, py, times, valid))

    mesh = make_mesh2(2, 4)
    fn = graph_sharded_match_fn(mesh, K, S)
    res_s, hist_s = fn(dg, du, *args, p)

    res_r, hist_r = jax.jit(
        match_and_histogram, static_argnums=(7, 8)
    )(dg, du, *args, p, K, S)

    np.testing.assert_array_equal(np.asarray(res_s.idx), np.asarray(res_r.idx))
    np.testing.assert_array_equal(np.asarray(res_s.breaks), np.asarray(res_r.breaks))
    for a, b in zip(hist_s, hist_r):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5)


def test_graph_sharded_rejects_bad_axis(setup):
    from reporter_tpu.parallel import check_ubodt_shardable

    arrays, ubodt = setup
    size = ubodt.packed.shape[0]
    bad = 3 if size % 3 else 5
    with pytest.raises(ValueError):
        check_ubodt_shardable(ubodt, bad)


def test_trace_count_exact_on_reentry(setup):
    """A trace that leaves a segment and re-enters it must count ONCE in
    trace_count (VERDICT r03 weak #7: the privacy cull keys on observation
    counts, so over-counting re-entries would weaken the guarantee).
    Verified against a host-side set-based count of the same matched
    segments."""
    import jax.numpy as jnp

    from reporter_tpu.ops.viterbi import MatchParams, match_batch
    from reporter_tpu.parallel import match_and_histogram

    arrays, ubodt = setup
    dg, du = arrays.to_device(), ubodt.to_device()
    p = MatchParams.from_config(MatcherConfig())

    # out-and-back drive: along row 2 then back the way it came -> the same
    # segments are entered twice by one trace
    cols = 5
    nodes = [2 * cols + c for c in [0, 1, 2, 3, 2, 1, 0]]
    xs, ys = arrays.node_x[nodes], arrays.node_y[nodes]
    t = np.linspace(0.0, 1.0, 14)
    px = np.interp(t, np.linspace(0, 1, len(xs)), xs)[None, :].astype(np.float32)
    py = np.interp(t, np.linspace(0, 1, len(ys)), ys)[None, :].astype(np.float32)
    times = (np.arange(14, dtype=np.float32) * 15.0)[None, :]
    valid = np.ones((1, 14), bool)

    S = len(arrays.seg_ids)
    res, hist = match_and_histogram(
        dg, du, jnp.asarray(px), jnp.asarray(py), jnp.asarray(times),
        jnp.asarray(valid), p, K, S,
    )
    # host-side oracle: distinct segments matched per trace
    idx = np.asarray(res.idx)
    edge = np.take_along_axis(np.asarray(res.cand.edge), np.maximum(idx, 0)[..., None], 2)[..., 0]
    want = np.zeros(S)
    for b in range(edge.shape[0]):
        segs = {int(arrays.edge_seg[e]) for e, i in zip(edge[b], idx[b]) if i >= 0
                and arrays.edge_seg[e] >= 0}
        for s in segs:
            want[s] += 1
    np.testing.assert_array_equal(np.asarray(hist.trace_count), want)
    # the drive really does revisit: some segment has >1 matched point runs
    assert want.max() == 1.0
