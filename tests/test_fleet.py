"""Fleet tier chaos suite (docs/serving-fleet.md): the session-affine
router + replicas are driven through the real HTTP seam and the fault
contracts are asserted end to end:

  (a) rendezvous affinity: the same vehicle uuid keeps landing on the
      same replica, and a killed replica remaps ONLY its own vehicles
  (b) kill-mid-load failover: requests keep succeeding through the
      router while a replica is hard-killed (passive ejection + active
      probing take it out of rotation)
  (c) graceful drain: a SIGTERM'd replica finishes its inflight work,
      answers new requests 503 {"status": "draining"} with Retry-After,
      exits 0, and the router rotates traffic off it (rolling restart
      brings the vehicle back to its primary)
  (d) the new faults.py points: router->replica connect refused is
      absorbed by failover, a flapped health probe is debounced, a
      slow-accepting replica is hedged around
  (e) keep-alive connection reuse on the shared pool is real (counted)
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from reporter_tpu import faults
from reporter_tpu.matching import MatcherConfig, SegmentMatcher
from reporter_tpu.serve import router as router_mod
from reporter_tpu.serve.router import FleetRouter, Replica, rendezvous_score
from reporter_tpu.serve.service import ReporterService
from reporter_tpu.stream.client import _post_json
from reporter_tpu.tiles.arrays import build_graph_arrays
from reporter_tpu.tiles.network import grid_city
from reporter_tpu.tiles.ubodt import build_ubodt
from reporter_tpu.utils.httppool import C_CONN_OPENED, C_CONN_REUSED, HttpPool

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    for p in faults.POINTS:
        monkeypatch.delenv("REPORTER_FAULT_" + p.upper(), raising=False)
    faults.reset()
    yield
    faults.reset()


@pytest.fixture(scope="module")
def engine():
    city = grid_city(rows=5, cols=5, spacing_m=150.0)
    arrays = build_graph_arrays(city, cell_size=100.0)
    ubodt = build_ubodt(arrays, delta=2000.0)
    return arrays, ubodt


def street_trace(arrays, uuid, row=2, n=8, t0=1000):
    nodes = [row * 5 + c for c in range(5)]
    t = np.linspace(0.05, 0.9, n)
    xs = np.interp(t, np.linspace(0, 1, 5), arrays.node_x[nodes])
    ys = np.interp(t, np.linspace(0, 1, 5), arrays.node_y[nodes])
    lat, lon = arrays.proj.to_latlon(xs, ys)
    return {
        "uuid": uuid,
        "trace": [
            {"lat": float(a), "lon": float(o), "time": t0 + 15 * i}
            for i, (a, o) in enumerate(zip(lat, lon))
        ],
        "match_options": {"mode": "auto", "report_levels": [0, 1],
                          "transition_levels": [0, 1]},
    }


class _Replica:
    """One in-process serve replica with a pinned replica id."""

    def __init__(self, arrays, ubodt, rid, port=0, **svc_kw):
        self.rid = rid
        prev = os.environ.get("REPORTER_REPLICA_ID")
        os.environ["REPORTER_REPLICA_ID"] = rid
        try:
            matcher = SegmentMatcher(arrays=arrays, ubodt=ubodt,
                                     config=MatcherConfig(), backend="cpu")
            self.svc = ReporterService(matcher, max_wait_ms=2.0, **svc_kw)
        finally:
            if prev is None:
                os.environ.pop("REPORTER_REPLICA_ID", None)
            else:
                os.environ["REPORTER_REPLICA_ID"] = prev
        self.httpd = self.svc.make_server("127.0.0.1", port)
        self.port = self.httpd.server_port
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()
        self.url = "http://127.0.0.1:%d" % self.port

    def kill(self):
        """Hard kill at the HTTP layer: stop accepting AND cut every
        live connection (what a SIGKILL's socket teardown looks like to
        the router)."""
        self.httpd.shutdown()
        self.httpd.close_lingering()
        self.httpd.server_close()

    def close(self):
        try:
            self.kill()
        except Exception:  # noqa: BLE001 - already killed by the test
            pass


class _Fleet:
    def __init__(self, arrays, ubodt, n=3, router_kw=None, **svc_kw):
        self.replicas = [
            _Replica(arrays, ubodt, "rep-%d" % i, **svc_kw)
            for i in range(n)]
        self.router = FleetRouter([r.url for r in self.replicas],
                                  probe_interval_s=0.2,
                                  **(router_kw or {}))
        self.router.start()
        self.httpd = self.router.make_server("127.0.0.1", 0)
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()
        self.url = "http://127.0.0.1:%d" % self.httpd.server_port

    def by_id(self, rid):
        return next(r for r in self.replicas if r.rid == rid)

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()
        self.router.stop()
        for r in self.replicas:
            r.close()


@pytest.fixture
def fleet_factory(engine):
    arrays, ubodt = engine
    fleets = []

    def make(n=3, router_kw=None, **svc_kw):
        f = _Fleet(arrays, ubodt, n=n, router_kw=router_kw, **svc_kw)
        fleets.append(f)
        return f

    yield make
    for f in fleets:
        f.close()


def post_json(url, payload, headers=None, timeout=30):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers=dict({"Content-Type": "application/json"},
                     **(headers or {})))
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, dict(r.headers), json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), json.loads(e.read().decode())


def get_json(url, timeout=10):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, dict(r.headers), json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), json.loads(e.read().decode())


# -- rendezvous hashing: the remap-confinement property ----------------------


def test_rendezvous_remap_confined_to_lost_replica():
    urls = ["http://h%d:8000" % i for i in range(5)]
    uuids = ["veh-%04d" % i for i in range(400)]

    def top(uuid, pool):
        return max(pool, key=lambda u: rendezvous_score(uuid, u))

    before = {u: top(u, urls) for u in uuids}
    dead = urls[2]
    survivors = [u for u in urls if u != dead]
    after = {u: top(u, survivors) for u in uuids}
    moved = {u for u in uuids if before[u] != after[u]}
    # EXACTLY the dead replica's vehicles move, nobody else's
    assert moved == {u for u in uuids if before[u] == dead}
    assert moved  # the dead replica did own some vehicles
    # and a removal never concentrates them on one survivor (HRW spreads)
    landed = {after[u] for u in moved}
    assert len(landed) > 1


def test_affinity_stable_and_replica_header(engine, fleet_factory):
    arrays, _ = engine
    fleet = fleet_factory()
    st, _hd, health = get_json(fleet.url + "/health")
    assert st == 200 and health["available"] == 3
    seen = {}
    for k in range(12):
        u = "veh-%d" % k
        st, hd, _body = post_json(fleet.url + "/report",
                                  street_trace(arrays, u))
        assert st == 200
        assert hd.get("X-Reporter-Replica") in ("rep-0", "rep-1", "rep-2")
        seen[u] = hd["X-Reporter-Replica"]
    assert len(set(seen.values())) > 1  # traffic actually spreads
    for u, rid in seen.items():
        st, hd, _body = post_json(fleet.url + "/report",
                                  street_trace(arrays, u))
        assert st == 200 and hd["X-Reporter-Replica"] == rid
    # the batch endpoint routes too (by its first trace's uuid)
    u0 = "veh-0"
    st, hd, body = post_json(
        fleet.url + "/trace_attributes_batch",
        {"traces": [street_trace(arrays, u0), street_trace(arrays, u0)]})
    assert st == 200 and len(body["results"]) == 2
    assert hd["X-Reporter-Replica"] == seen[u0]


def test_kill_mid_load_failover_and_bounded_remap(engine, fleet_factory):
    arrays, _ = engine
    fleet = fleet_factory()
    uuids = ["veh-%d" % k for k in range(18)]
    before = {}
    for u in uuids:
        st, hd, _ = post_json(fleet.url + "/report", street_trace(arrays, u))
        assert st == 200
        before[u] = hd["X-Reporter-Replica"]
    dead_rid = before[uuids[0]]
    fleet.by_id(dead_rid).kill()
    after = {}
    for u in uuids:  # no failed requests during the failover window
        st, hd, _ = post_json(fleet.url + "/report", street_trace(arrays, u))
        assert st == 200, u
        after[u] = hd["X-Reporter-Replica"]
    moved = {u for u in uuids if after[u] != before[u]}
    assert moved == {u for u in uuids if before[u] == dead_rid}
    assert dead_rid not in after.values()
    # the prober notices and /health reports the hole
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        st, _hd, health = get_json(fleet.url + "/health")
        if health["available"] == 2:
            break
        time.sleep(0.1)
    assert health["available"] == 2


def test_drain_rotates_off_and_rolling_restart_returns(engine, fleet_factory):
    arrays, ubodt = engine
    fleet = fleet_factory()
    uuids = ["veh-%d" % k for k in range(12)]
    before = {}
    for u in uuids:
        st, hd, _ = post_json(fleet.url + "/report", street_trace(arrays, u))
        assert st == 200
        before[u] = hd["X-Reporter-Replica"]
    target_rid = before[uuids[0]]
    target = fleet.by_id(target_rid)
    target.svc.begin_drain()
    # the replica itself now answers 503 "draining" (distinct from
    # unhealthy) with a Retry-After hint
    st, hd, body = get_json(target.url + "/health")
    assert st == 503 and body["status"] == "draining"
    st, hd, body = post_json(target.url + "/report",
                             street_trace(arrays, uuids[0]))
    assert st == 503 and body.get("status") == "draining"
    assert int(hd.get("Retry-After", 0)) >= 1
    # through the router: its vehicles keep succeeding (failover
    # re-dispatch absorbs the 503s), nobody else moves
    for u in uuids:
        st, hd, _ = post_json(fleet.url + "/report", street_trace(arrays, u))
        assert st == 200, u
        if before[u] != target_rid:
            assert hd["X-Reporter-Replica"] == before[u]
        else:
            assert hd["X-Reporter-Replica"] != target_rid
    # the prober sees the drain (no ejection bookkeeping: deliberate)
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        rep = next(r for r in fleet.router.replicas
                   if (r.id or "") == target_rid)
        if rep.state == "draining":
            break
        time.sleep(0.1)
    assert rep.state == "draining"
    # rolling restart: the drained process goes away, a fresh replica
    # binds the SAME port/url — the vehicle comes back to its primary
    port = target.port
    target.kill()
    replacement = _Replica(arrays, ubodt, target_rid, port=port)
    try:
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            fleet.router.probe_all()
            if rep.available():
                break
            time.sleep(0.1)
        assert rep.available()
        st, hd, _ = post_json(fleet.url + "/report",
                              street_trace(arrays, uuids[0]))
        assert st == 200 and hd["X-Reporter-Replica"] == target_rid
    finally:
        replacement.close()


# -- the new fault-injection points ------------------------------------------


def test_router_connect_refused_absorbed_by_failover(
        engine, fleet_factory, monkeypatch):
    arrays, _ = engine
    fleet = fleet_factory()
    n0 = router_mod.C_FAILOVER.labels("network").value
    monkeypatch.setenv("REPORTER_FAULT_ROUTER_CONNECT", "refused:1")
    st, hd, _ = post_json(fleet.url + "/report",
                          street_trace(arrays, "veh-0"))
    assert st == 200  # the injected refusal never reached the client
    assert router_mod.C_FAILOVER.labels("network").value >= n0 + 1


def test_health_flap_is_debounced_then_sustained_failure_ejects(
        engine, fleet_factory, monkeypatch):
    fleet = fleet_factory()
    first = fleet.router.replicas[0]
    assert first.available()
    # ONE flapped probe: below the unhealthy_after=2 debounce, the
    # replica must stay in rotation
    monkeypatch.setenv("REPORTER_FAULT_HEALTH_FLAP", "1")
    fleet.router.probe_all()
    assert first.available() and first.state == "healthy"
    # sustained flapping: now it must go
    monkeypatch.setenv("REPORTER_FAULT_HEALTH_FLAP", "always")
    faults.reset()
    fleet.router.probe_all()
    fleet.router.probe_all()
    assert first.state == "unhealthy" and not first.available()
    # recovery is debounced too (healthy_after=2): one good probe is not
    # enough, two are
    monkeypatch.delenv("REPORTER_FAULT_HEALTH_FLAP")
    fleet.router.probe_all()
    assert first.state == "unhealthy"
    fleet.router.probe_all()
    assert first.state == "healthy" and first.available()


def test_slow_accept_is_hedged_around(engine, fleet_factory, monkeypatch):
    arrays, _ = engine
    fleet = fleet_factory(router_kw={"hedge_ms": 100.0})
    hedges0 = router_mod.C_HEDGES.value
    wins0 = router_mod.C_HEDGE_WINS.value
    # the primary's NEXT /report stalls 1.2 s at the door; the hedge
    # fires at 100 ms and the second-ranked replica answers instead
    monkeypatch.setenv("REPORTER_FAULT_REPLICA_SLOW_ACCEPT", "1.2:1")
    t0 = time.monotonic()
    st, _hd, _ = post_json(fleet.url + "/report",
                           street_trace(arrays, "veh-7"))
    took = time.monotonic() - t0
    assert st == 200
    assert took < 1.0, "hedge did not cut the straggler (took %.2fs)" % took
    assert router_mod.C_HEDGES.value >= hedges0 + 1
    assert router_mod.C_HEDGE_WINS.value >= wins0 + 1


def test_router_sheds_when_saturated(engine, fleet_factory, monkeypatch):
    arrays, _ = engine
    fleet = fleet_factory(router_kw={"max_inflight": 1})
    shed0 = router_mod.C_SHED.value
    monkeypatch.setenv("REPORTER_FAULT_REPLICA_SLOW_ACCEPT", "0.8:1")
    results = []

    def hit(u):
        results.append(post_json(fleet.url + "/report",
                                 street_trace(arrays, u)))

    t1 = threading.Thread(target=hit, args=("veh-1",))
    t1.start()
    time.sleep(0.25)  # the slow request is now holding the only slot
    st, hd, body = post_json(fleet.url + "/report",
                             street_trace(arrays, "veh-2"))
    t1.join()
    assert st == 429
    assert int(hd.get("Retry-After", 0)) >= 1
    assert router_mod.C_SHED.value >= shed0 + 1
    assert results[0][0] == 200  # the accepted request still succeeded


def test_no_replica_available_is_503(engine):
    router = FleetRouter(["http://127.0.0.1:9"])  # discard port: refused
    router.probe_all()
    httpd = router.make_server("127.0.0.1", 0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    url = "http://127.0.0.1:%d" % httpd.server_port
    try:
        st, hd, body = get_json(url + "/health")
        assert st == 503 and body["status"] == "unavailable"
        st, hd, body = post_json(
            url + "/report", {"uuid": "v", "trace": [], "match_options": {}})
        assert st == 503
        assert int(hd.get("Retry-After", 0)) >= 1
    finally:
        httpd.shutdown()
        httpd.server_close()
        router.stop()


# -- health statuses are distinct --------------------------------------------


def test_health_draining_vs_unhealthy_statuses(engine):
    arrays, ubodt = engine
    rep = _Replica(arrays, ubodt, "rep-x")
    try:
        st, body = rep.svc.handle_health()
        assert st == 200 and body["status"] == "ok"
        assert body["replica"] == "rep-x"
        rep.svc.unhealthy_reason = "batcher thread died: boom"
        st, body = rep.svc.handle_health()
        assert st == 503 and body["status"] == "unhealthy"
        rep.svc.unhealthy_reason = None
        rep.svc.begin_drain()
        st, body = rep.svc.handle_health()
        assert st == 503 and body["status"] == "draining"
        # unhealthy outranks draining (a crashed batcher needs a restart
        # even mid-drain)
        rep.svc.unhealthy_reason = "batcher thread died: boom"
        st, body = rep.svc.handle_health()
        assert st == 503 and body["status"] == "unhealthy"
    finally:
        rep.close()


# -- keep-alive connection reuse ---------------------------------------------


def test_connection_reuse_is_real_and_counted(engine):
    arrays, ubodt = engine
    rep = _Replica(arrays, ubodt, "rep-ka")
    try:
        opened0 = C_CONN_OPENED.labels("matcher").value
        reused0 = C_CONN_REUSED.labels("matcher").value
        for k in range(6):
            out = _post_json(rep.url + "/report",
                             street_trace(arrays, "veh-%d" % k))
            assert out is not None and "segment_matcher" in out
        opened = C_CONN_OPENED.labels("matcher").value - opened0
        reused = C_CONN_REUSED.labels("matcher").value - reused0
        # 6 sequential requests: one connect, five keep-alive reuses
        assert opened == 1
        assert reused >= 5
    finally:
        rep.close()


def test_pool_recovers_transparently_from_stale_keepalive(engine):
    arrays, ubodt = engine
    pool = HttpPool()
    rep = _Replica(arrays, ubodt, "rep-stale")
    body = json.dumps(street_trace(arrays, "veh-1")).encode()
    try:
        st, _h, _b = pool.request(
            "POST", rep.url + "/report", body=body,
            headers={"Content-Type": "application/json"}, target="t")
        assert st == 200
        # the server cuts the pooled connection behind our back (idle
        # keep-alive churn); the next request must transparently retry
        # on a fresh connection, not error
        rep.httpd.close_lingering()
        time.sleep(0.1)
        st, _h, _b = pool.request(
            "POST", rep.url + "/report", body=body,
            headers={"Content-Type": "application/json"}, target="t")
        assert st == 200
    finally:
        pool.close()
        rep.close()


# -- graceful drain, full process contract -----------------------------------


def test_sigterm_drain_finishes_inflight_then_exits_zero(engine, tmp_path):
    """The acceptance contract: SIGTERM -> inflight request completes
    (no client-visible reset), new requests answer 503 "draining" with
    Retry-After, /health flips to "draining", exit code 0."""
    arrays, _ = engine
    conf = {
        "network": {"type": "grid", "rows": 5, "cols": 5,
                    "spacing_m": 150.0},
        "matcher": {"search_radius": 50.0},
        "backend": "cpu",
        # a 1.5 s batch-fill window makes every /report spend ~1.5 s
        # inside the batcher: the inflight request the drain must finish
        "batch": {"max_batch": 64, "max_wait_ms": 1500},
        "warmup": False,
    }
    conf_path = tmp_path / "config.json"
    conf_path.write_text(json.dumps(conf))
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
               REPORTER_REPLICA_ID="rep-drain",
               REPORTER_DRAIN_GRACE_S="15")
    proc = subprocess.Popen(
        [sys.executable, "-m", "reporter_tpu.serve", str(conf_path),
         "127.0.0.1:0"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    try:
        # the CLI binds :0; recover the bound port from the log line
        port = None
        deadline = time.monotonic() + 60
        buf = b""
        while time.monotonic() < deadline and port is None:
            line = proc.stdout.readline()
            if not line:
                time.sleep(0.05)
                continue
            buf += line
            if b"service on 127.0.0.1:" in line:
                port = int(line.split(b"127.0.0.1:")[1].split()[0])
        assert port, "no bind line in serve output: %r" % buf
        url = "http://127.0.0.1:%d" % port
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            try:
                st, _h, h = get_json(url + "/health", timeout=2)
                if st == 200 and h.get("backend"):
                    break
            except Exception:  # noqa: BLE001 - still booting
                pass
            time.sleep(0.25)
        else:
            pytest.fail("service never became healthy")

        inflight = {}

        def slow_request():
            inflight["result"] = post_json(
                url + "/report", street_trace(arrays, "veh-inflight"),
                timeout=30)

        t = threading.Thread(target=slow_request)
        t.start()
        time.sleep(0.6)  # the request is inside its 1.5 s batch window
        proc.send_signal(signal.SIGTERM)
        time.sleep(0.3)
        # new request during the drain window: refused, retryable,
        # explicitly "draining"
        st, hd, body = post_json(url + "/report",
                                 street_trace(arrays, "veh-late"),
                                 timeout=10)
        assert st == 503 and body.get("status") == "draining"
        assert int(hd.get("Retry-After", 0)) >= 1
        st, _hd, body = get_json(url + "/health", timeout=10)
        assert st == 503 and body["status"] == "draining"
        # the inflight request finished normally — no reset, no 5xx
        t.join(timeout=20)
        assert not t.is_alive()
        st, hd, body = inflight["result"]
        assert st == 200 and "segment_matcher" in body
        assert hd.get("X-Reporter-Replica") == "rep-drain"
        assert proc.wait(timeout=20) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()


# -- geo-aware ranking (flag-gated; docs/serving-fleet.md "Sharded
# tables") -----------------------------------------------------------------


def test_geo_off_is_bitforbit_rendezvous(monkeypatch):
    """With REPORTER_ROUTER_GEO unset the ranking is the PR 9 rendezvous
    hash exactly, for every uuid — even when replicas advertise shards
    and requests carry coordinates."""
    monkeypatch.delenv("REPORTER_ROUTER_GEO", raising=False)
    router = FleetRouter(
        ["http://127.0.0.1:1", "http://127.0.0.1:2", "http://127.0.0.1:3"],
        probe_interval_s=3600.0)
    try:
        assert router.geo_routing is False
        for i, r in enumerate(router.replicas):
            r.shard = "%d/3" % i
        for k in range(50):
            uuid = "veh-%d" % k
            want = sorted(
                router.replicas,
                key=lambda r: rendezvous_score(uuid, r.url), reverse=True)
            assert [r.url for r in router.ranked(uuid)] == \
                [r.url for r in want]
            # geo is never even computed with the flag off: the caller
            # passes None, and an explicit geo changes nothing either
            assert [r.url for r in router.ranked(uuid, (52.5, 13.4))] == \
                [r.url for r in want]
    finally:
        router.stop()


def test_geo_on_prefers_shard_owner(monkeypatch):
    """Flag on: the replica whose advertised shard covers the request's
    geographic cell ranks first; the rendezvous hash still orders the
    rest, the mapping is stable per cell, and uuids without coordinates
    keep plain rendezvous ranking."""
    from reporter_tpu.serve.router import C_GEO, geo_cell

    monkeypatch.setenv("REPORTER_ROUTER_GEO", "1")
    monkeypatch.setenv("REPORTER_ROUTER_GEO_CELL_DEG", "0.25")
    router = FleetRouter(
        ["http://127.0.0.1:1", "http://127.0.0.1:2", "http://127.0.0.1:3"],
        probe_interval_s=3600.0)
    try:
        assert router.geo_routing is True
        for i, r in enumerate(router.replicas):
            r.shard = "%d/3" % i
        geo = (52.5, 13.4)
        cell = geo_cell(geo[0], geo[1], 0.25)
        owner = next(r for r in router.replicas
                     if router._geo_pref(r, cell))
        g0 = sum(C_GEO.labels(o).value for o in ("steered", "aligned"))
        for k in range(20):
            order = router.ranked("veh-%d" % k, geo)
            assert order[0] is owner
            # the tail is still rendezvous-ordered
            tail = [r for r in router.replicas if r is not owner]
            want = sorted(tail, key=lambda r: rendezvous_score(
                "veh-%d" % k, r.url), reverse=True)
            assert [r.url for r in order[1:]] == [r.url for r in want]
        assert sum(C_GEO.labels(o).value
                   for o in ("steered", "aligned")) == g0 + 20
        # no coordinate -> plain rendezvous, even with the flag on
        for k in range(20):
            uuid = "veh-%d" % k
            want = sorted(
                router.replicas,
                key=lambda r: rendezvous_score(uuid, r.url), reverse=True)
            assert [r.url for r in router.ranked(uuid)] == \
                [r.url for r in want]
        # a replica with no (or junk) shard never gets the bonus
        assert router._geo_pref(Replica("http://x:1"), cell) == 0
        junk = Replica("http://x:2")
        junk.shard = "weird"
        assert router._geo_pref(junk, cell) == 0
    finally:
        router.stop()
