import http.server
import os
import threading

import pytest

from reporter_tpu.anonymise import (
    CSV_HEADER,
    SegmentObservation,
    TimeQuantisedTile,
    observations_for_report,
    privacy_cull,
    make_store,
    DirStore,
    HttpStore,
)
from reporter_tpu.anonymise.tiles import tile_csv, usable_report
from reporter_tpu.tiles.segment_id import INVALID_SEGMENT_ID, pack_segment_id, get_tile_id

SID = pack_segment_id(1, 1000, 5)
SID2 = pack_segment_id(1, 1000, 6)


def rep(t0, t1, sid=SID, next_id=None, length=200.0, queue=0.0):
    r = {"id": sid, "t0": t0, "t1": t1, "length": length, "queue_length": queue}
    if next_id is not None:
        r["next_id"] = next_id
    return r


class TestObservations:
    def test_single_bucket(self):
        out = list(observations_for_report(rep(100, 160, next_id=SID2), 3600, "src"))
        assert len(out) == 1
        tile, obs = out[0]
        assert tile == TimeQuantisedTile(0, get_tile_id(SID))
        assert obs.segment_id == SID and obs.next_segment_id == SID2
        assert obs.duration == 60 and obs.count == 1
        assert obs.min_timestamp == 100 and obs.max_timestamp == 160

    def test_bucket_spanning(self):
        out = list(observations_for_report(rep(3590, 3610), 3600, "src"))
        assert [t.time_start for t, _ in out] == [0, 3600]

    def test_max_buckets_guard(self):
        out = list(observations_for_report(rep(0, 4 * 3600), 3600, "src", max_buckets=2))
        assert out == []

    def test_no_next_id_uses_invalid(self):
        _, obs = next(iter(observations_for_report(rep(10, 20), 3600, "src")))
        assert obs.next_segment_id == INVALID_SEGMENT_ID

    def test_tile_path(self):
        tile = TimeQuantisedTile(7200, get_tile_id(SID))
        assert tile.path(3600) == "7200_10799/1/1000"

    def test_usable_report_filter(self):
        assert usable_report(rep(10, 20))
        assert not usable_report(rep(0, 20))          # t0 not > 0
        assert not usable_report(rep(10, 10.2))       # too short
        assert not usable_report(rep(10, 20, length=0))
        assert not usable_report(rep(10, 20, queue=-1))


class TestPrivacyCull:
    def obs(self, sid, next_id, t=100):
        return SegmentObservation(sid, next_id, 10, 1, 200.0, 0.0, t, t + 10, "s", "AUTO")

    def test_cull_below_privacy(self):
        rows = [self.obs(SID, SID2), self.obs(SID, SID2), self.obs(SID2, SID)]
        out = privacy_cull(rows, 2)
        assert len(out) == 2
        assert all(o.segment_id == SID for o in out)

    def test_privacy_one_keeps_all(self):
        rows = [self.obs(SID, SID2), self.obs(SID2, SID)]
        assert len(privacy_cull(rows, 1)) == 2

    def test_cull_everything(self):
        rows = [self.obs(SID, SID2)]
        assert privacy_cull(rows, 2) == []

    def test_privacy_property_randomized(self):
        """The privacy promise, checked as a property over random inputs:
        for every (segment_id, next_segment_id) pair, the output carries
        either ALL of its observations (count >= privacy) or NONE
        (count < privacy) -- never a partial group -- and the output is
        sorted by the contract key."""
        import collections
        import numpy as np

        rng = np.random.default_rng(17)
        for trial in range(25):
            privacy = int(rng.integers(1, 5))
            ids = [int(v) for v in rng.integers(1, 9, 2)]
            rows = []
            for _ in range(int(rng.integers(0, 40))):
                a, b = int(rng.choice(ids + [3, 4, 5])), int(rng.choice(ids + [3, 4, 5]))
                t = int(rng.integers(0, 3600))
                rows.append(SegmentObservation(
                    a, b, 10, 1, float(rng.integers(20, 400)), 0.0,
                    t, t + 10, "s", "AUTO"))
            counts = collections.Counter(
                (r.segment_id, r.next_segment_id) for r in rows)
            out = privacy_cull(list(rows), privacy)
            out_counts = collections.Counter(
                (r.segment_id, r.next_segment_id) for r in out)
            for pair, n in counts.items():
                want = n if n >= privacy else 0
                assert out_counts.get(pair, 0) == want, (trial, pair, n, privacy)
            assert [r.sort_key() for r in out] == sorted(
                r.sort_key() for r in out), (trial, "output not sorted")

    def test_csv_roundtrip(self):
        rows = [self.obs(SID, SID2), self.obs(SID, SID2)]
        text = tile_csv(rows)
        lines = text.strip().split("\n")
        assert lines[0] == CSV_HEADER
        back = SegmentObservation.from_csv_row(lines[1])
        assert back == rows[0]


class TestStores:
    def test_dir_store(self, tmp_path):
        store = make_store("dir:%s" % tmp_path)
        store.put("7200_10799/1/1000/src.abc", "hello\n")
        assert (tmp_path / "7200_10799" / "1" / "1000" / "src.abc").read_text() == "hello\n"

    def test_make_store_kinds(self, tmp_path):
        assert isinstance(make_store(str(tmp_path)), DirStore)
        assert isinstance(make_store("http://x/y"), HttpStore)
        assert make_store("s3://bucket").bucket == "bucket"

    def test_http_store_posts(self):
        received = {}

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_POST(self):
                received["path"] = self.path
                received["body"] = self.rfile.read(int(self.headers["Content-Length"])).decode()
                self.send_response(200)
                self.end_headers()
                self.wfile.write(b"ok")

            def log_message(self, *a):
                pass

        srv = http.server.HTTPServer(("127.0.0.1", 0), Handler)
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        try:
            store = HttpStore("http://127.0.0.1:%d/store" % srv.server_port)
            store.put("0_3599/1/1000/src.x", "csv,data\n")
            assert received["path"] == "/store/0_3599/1/1000/src.x"
            assert received["body"] == "csv,data\n"
        finally:
            srv.shutdown()

    def test_http_store_4xx_raises(self):
        class Handler(http.server.BaseHTTPRequestHandler):
            def do_POST(self):
                self.send_response(400)
                self.end_headers()

            def log_message(self, *a):
                pass

        srv = http.server.HTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        try:
            store = HttpStore("http://127.0.0.1:%d" % srv.server_port)
            with pytest.raises(Exception):
                store.put("k", "v")
        finally:
            srv.shutdown()


def test_s3_prefix_split():
    from reporter_tpu.anonymise import make_store
    s = make_store("s3://mybucket/tiles/v1")
    assert s.bucket == "mybucket" and s.prefix == "tiles/v1"


def test_datastore_stub_receives_http_tiles(tmp_path):
    """End-to-end egress check: HttpStore -> tools/datastore_stub -> files
    on disk keyed by tile path (the echo server the reference TODO'd,
    tests/circle.sh:13-16)."""
    import sys
    import threading

    sys.path.insert(0, "tools")
    try:
        from datastore_stub import make_server
    finally:
        sys.path.pop(0)

    from reporter_tpu.anonymise.storage import HttpStore

    root = tmp_path / "ds"
    srv = make_server(str(root), host="127.0.0.1", port=0)
    port = srv.server_address[1]
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        store = HttpStore("http://127.0.0.1:%d/tiles" % port)
        store.put("1459998000_1460001599/1/45777/SRC.abc", "h,e,a,d\n1,2,3,4\n")
        got = root / "tiles" / "1459998000_1460001599" / "1" / "45777" / "SRC.abc"
        assert got.exists() and got.read_bytes().startswith(b"h,e,a,d")
    finally:
        srv.shutdown()
        srv.server_close()
