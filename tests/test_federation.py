"""Fleet observability plane (docs/observability.md "Fleet
observability"): metrics federation, cross-hop trace stitching, and the
client-truth fleet SLO, driven through the real router + replica HTTP
seam:

  (a) the federated render: every replica family re-rendered under a
      ``replica`` label, parseable by the shared quantile parser, no
      duplicate # TYPE metadata next to the router's own families
  (b) a dead replica's LAST snapshot stays in the render, labeled stale
      with a rising age gauge — never silently dropped
  (c) client truth: a request that failed over and succeeded is
      fleet-good at the router while the burned replica's own engine
      records the bad — and the delta shows up in the masking-debt gauge
  (d) cross-hop stitching: the router's hop spans (every dispatch
      attempt, hedge legs with the loser marked cancelled) splice the
      serving replica's span tree under them at GET /debug/traces?id=
  (e) /debug/attrib + /debug/profile route through the router with a
      ?replica=<id> selector (400 without, 404 listing known ids)
  (f) the federation-consistency invariant over REAL subprocess
      replicas: per-replica federated counters equal the client-observed
      per-replica distribution, and shutdown dumps embed each replica's
      id (no collisions on a shared dump dir)
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from reporter_tpu import faults
from reporter_tpu.matching import MatcherConfig, SegmentMatcher
from reporter_tpu.obs import federation as obs_fed
from reporter_tpu.obs import flight as obs_flight
from reporter_tpu.obs.quantile import (
    hist_buckets,
    hist_quantile,
    merge_parsed,
    parse_metrics,
)
from reporter_tpu.serve.router import FleetRouter
from reporter_tpu.serve.service import ReporterService
from reporter_tpu.tiles.arrays import build_graph_arrays
from reporter_tpu.tiles.network import grid_city
from reporter_tpu.tiles.ubodt import build_ubodt

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    for p in faults.POINTS:
        monkeypatch.delenv("REPORTER_FAULT_" + p.upper(), raising=False)
    faults.reset()
    yield
    faults.reset()


@pytest.fixture(scope="module")
def engine():
    city = grid_city(rows=5, cols=5, spacing_m=150.0)
    arrays = build_graph_arrays(city, cell_size=100.0)
    ubodt = build_ubodt(arrays, delta=2000.0)
    return arrays, ubodt


def street_trace(arrays, uuid, row=2, n=8, t0=1000):
    nodes = [row * 5 + c for c in range(5)]
    t = np.linspace(0.05, 0.9, n)
    xs = np.interp(t, np.linspace(0, 1, 5), arrays.node_x[nodes])
    ys = np.interp(t, np.linspace(0, 1, 5), arrays.node_y[nodes])
    lat, lon = arrays.proj.to_latlon(xs, ys)
    return {
        "uuid": uuid,
        "trace": [
            {"lat": float(a), "lon": float(o), "time": t0 + 15 * i}
            for i, (a, o) in enumerate(zip(lat, lon))
        ],
        "match_options": {"mode": "auto", "report_levels": [0, 1],
                          "transition_levels": [0, 1]},
    }


class _Replica:
    def __init__(self, arrays, ubodt, rid, port=0, **svc_kw):
        self.rid = rid
        prev = os.environ.get("REPORTER_REPLICA_ID")
        os.environ["REPORTER_REPLICA_ID"] = rid
        try:
            matcher = SegmentMatcher(arrays=arrays, ubodt=ubodt,
                                     config=MatcherConfig(), backend="cpu")
            self.svc = ReporterService(matcher, max_wait_ms=2.0, **svc_kw)
        finally:
            if prev is None:
                os.environ.pop("REPORTER_REPLICA_ID", None)
            else:
                os.environ["REPORTER_REPLICA_ID"] = prev
        self.httpd = self.svc.make_server("127.0.0.1", port)
        self.port = self.httpd.server_port
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()
        self.url = "http://127.0.0.1:%d" % self.port

    def kill(self):
        self.httpd.shutdown()
        self.httpd.close_lingering()
        self.httpd.server_close()

    def close(self):
        try:
            self.kill()
        except Exception:  # noqa: BLE001 - already killed by the test
            pass


class _Fleet:
    def __init__(self, arrays, ubodt, n=3, router_kw=None, **svc_kw):
        self.replicas = [
            _Replica(arrays, ubodt, "fed-rep-%d" % i, **svc_kw)
            for i in range(n)]
        self.router = FleetRouter([r.url for r in self.replicas],
                                  probe_interval_s=0.2,
                                  **(router_kw or {}))
        self.router.federator.pull_interval_s = 0.3
        self.router.federator.stale_after_s = 0.9
        self.router.start()
        self.httpd = self.router.make_server("127.0.0.1", 0)
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()
        self.url = "http://127.0.0.1:%d" % self.httpd.server_port

    def by_id(self, rid):
        return next(r for r in self.replicas if r.rid == rid)

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()
        self.router.stop()
        for r in self.replicas:
            r.close()


@pytest.fixture
def fleet_factory(engine):
    arrays, ubodt = engine
    fleets = []

    def make(n=3, router_kw=None, **svc_kw):
        f = _Fleet(arrays, ubodt, n=n, router_kw=router_kw, **svc_kw)
        fleets.append(f)
        return f

    yield make
    for f in fleets:
        f.close()


def post_json(url, payload, headers=None, timeout=30):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers=dict({"Content-Type": "application/json"},
                     **(headers or {})))
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, dict(r.headers), json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), json.loads(e.read().decode())


def get_raw(url, timeout=30):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def get_json(url, timeout=30):
    st, body = get_raw(url, timeout)
    return st, json.loads(body)


# -- (a) the federated render -------------------------------------------------


def test_render_snapshots_unit():
    snaps = {
        "rep-a": {
            "m_total": {"type": "counter", "help": "a counter",
                        "labelnames": ["endpoint"],
                        "samples": [[["report"], 3.0]]},
            "m_lat": {"type": "histogram", "help": "a hist",
                      "labelnames": [],
                      "samples": [[[], {"buckets": [0.1, 1.0],
                                        "counts": [2, 1, 1],
                                        "sum": 1.5, "count": 4}]]},
        },
        'rep-"b"': {  # label escaping must hold
            "m_total": {"type": "counter", "help": "a counter",
                        "labelnames": ["endpoint"],
                        "samples": [[["report"], 5.0]]},
        },
    }
    text = obs_fed.render_snapshots(snaps)
    m = parse_metrics(text)
    assert m["m_total"][(("endpoint", "report"),
                        ("replica", "rep-a"))] == 3.0
    assert m["m_total"][(("endpoint", "report"),
                        ("replica", 'rep-\\"b\\"'))] == 5.0
    # histogram rendered cumulatively with the replica label on every line
    b = hist_buckets(m, "m_lat", match={"replica": "rep-a"})
    assert b == [(0.1, 2.0), (1.0, 3.0), (float("inf"), 4.0)]
    assert m["m_lat_count"][(("replica", "rep-a"),)] == 4.0
    # skip_meta suppresses duplicated metadata, samples still render
    text2 = obs_fed.render_snapshots(snaps, skip_meta={"m_total"})
    assert "# TYPE m_total" not in text2
    assert 'm_total{replica="rep-a"' in text2


def test_merge_parsed_sums_across_targets():
    a = parse_metrics("x_total 3\n"
                      'h_bucket{le="0.1"} 1\nh_bucket{le="+Inf"} 2\n')
    b = parse_metrics("x_total 4\n"
                      'h_bucket{le="0.1"} 2\nh_bucket{le="+Inf"} 3\n')
    m = merge_parsed([a, b])
    assert m["x_total"][()] == 7.0
    assert hist_buckets(m, "h") == [(0.1, 3.0), (float("inf"), 5.0)]
    # merge_children collapses several children of one family
    fed = parse_metrics(
        'h_bucket{replica="r0",le="0.1"} 1\n'
        'h_bucket{replica="r0",le="+Inf"} 2\n'
        'h_bucket{replica="r1",le="0.1"} 3\n'
        'h_bucket{replica="r1",le="+Inf"} 5\n')
    assert hist_buckets(fed, "h", merge_children=True) == [
        (0.1, 4.0), (float("inf"), 7.0)]
    assert hist_quantile(hist_buckets(fed, "h", merge_children=True),
                         0.5) is not None


def test_router_metrics_federated(engine, fleet_factory):
    arrays, _ = engine
    fleet = fleet_factory(n=2)
    for k in range(6):
        st, _hd, _b = post_json(fleet.url + "/report",
                                street_trace(arrays, "veh-%d" % k))
        assert st == 200
    st, text = get_raw(fleet.url + "/metrics?pull=1")
    assert st == 200
    # the replica label rides every federated family; the router's own
    # families render exactly once (no duplicated # TYPE metadata)
    assert 'replica="fed-rep-0"' in text and 'replica="fed-rep-1"' in text
    tnames = [l.split()[2] for l in text.splitlines()
              if l.startswith("# TYPE")]
    assert len(tnames) == len(set(tnames))
    m = parse_metrics(text)
    assert "reporter_fleet_slo_requests_total" in m
    assert "reporter_fleet_slo_masking_debt" in m
    ages = {dict(lv)["replica"]: v for lv, v in
            m["reporter_federation_snapshot_age_seconds"].items()}
    assert set(ages) == {"fed-rep-0", "fed-rep-1"}
    assert all(v >= 0 for v in ages.values())


# -- (b) staleness: the dead replica's last snapshot survives -----------------


def test_dead_replica_snapshot_kept_and_labeled_stale(engine, fleet_factory):
    arrays, _ = engine
    fleet = fleet_factory(n=2)
    for k in range(4):
        st, _hd, _b = post_json(fleet.url + "/report",
                                street_trace(arrays, "veh-%d" % k))
        assert st == 200
    fleet.router.federator.pull_all()
    victim = fleet.replicas[1]
    victim.kill()
    time.sleep(1.0)  # > stale_after_s (0.9), pulls now failing
    st, text = get_raw(fleet.url + "/metrics?pull=1")
    m = parse_metrics(text)
    key = (("replica", victim.rid),)
    age1 = m["reporter_federation_snapshot_age_seconds"][key]
    assert m["reporter_federation_snapshot_stale"][key] == 1.0
    assert age1 > 0.9
    # the final snapshot is still in the render — dead, not dropped
    assert any(dict(lv).get("replica") == victim.rid
               for lv in m.get("reporter_requests_total", {}))
    time.sleep(0.5)
    st, text = get_raw(fleet.url + "/metrics?pull=1")
    m2 = parse_metrics(text)
    assert m2["reporter_federation_snapshot_age_seconds"][key] > age1
    # the live replica stays fresh
    live = (("replica", fleet.replicas[0].rid),)
    assert m2["reporter_federation_snapshot_stale"][live] == 0.0


# -- (c) client truth + masking debt ------------------------------------------


def test_failover_masked_request_is_fleet_good_replica_bad(
        engine, fleet_factory):
    arrays, ubodt = engine
    fleet = fleet_factory(n=2)
    # find a vehicle whose rendezvous primary is replica 0, then drain
    # that replica: its 503 "draining" burns ITS budget while the router
    # fails the request over and the CLIENT sees a clean 200
    uuid = next("veh-m%d" % k for k in range(64)
                if fleet.router.ranked("veh-m%d" % k)[0].url
                == fleet.replicas[0].url)
    st, _hd, _b = post_json(fleet.url + "/report",
                            street_trace(arrays, uuid))
    assert st == 200
    fleet.replicas[0].svc.begin_drain()
    st, hd, _b = post_json(fleet.url + "/report",
                           street_trace(arrays, uuid))
    assert st == 200  # fleet-good: the failover masked the drain refusal
    assert hd["X-Reporter-Replica"] == fleet.replicas[1].rid
    fleet.router.federator.pull_all()
    st, slo = get_json(fleet.url + "/debug/slo")
    assert st == 200 and slo["scope"] == "fleet"
    rep = slo["routes"]["report"]
    assert rep["bad"] == 0 and rep["good"] >= 2
    # ...but the masking debt bills the replica-side burn the failover hid
    assert slo["masking_debt"]["availability"] > 0
    st, statusz = get_json(fleet.url + "/statusz")
    assert statusz["masking_debt"]["availability"] > 0
    # and the gauge is on the federated scrape
    st, text = get_raw(fleet.url + "/metrics")
    m = parse_metrics(text)
    assert m["reporter_fleet_slo_masking_debt"][
        (("objective", "availability"),)] > 0


def test_injected_replica_shed_is_masked_and_billed(engine, fleet_factory,
                                                    monkeypatch):
    """The deterministic fleet-good/replica-bad fixture the rehearsal
    leans on: an injected admission shed 429s at ONE replica, the router
    rotates onward, the client sees 200 — and the debt shows up."""
    arrays, _ = engine
    fleet = fleet_factory(n=2)
    monkeypatch.setenv("REPORTER_FAULT_REPLICA_SHED", "1")
    faults.reset()
    st, hd, _b = post_json(fleet.url + "/report",
                           street_trace(arrays, "veh-shed"))
    assert st == 200  # masked: the shed never reached the client
    tid = hd["X-Reporter-Trace"]
    fleet.router.federator.pull_all()
    st, slo = get_json(fleet.url + "/debug/slo")
    assert slo["masking_debt"]["availability"] > 0
    # and the stitched trace names the shedding hop
    st, out = get_json(fleet.url + "/debug/traces?id=%s" % tid)
    assert st == 200
    hops = out["stitched"]["hops"]
    assert any(h["outcome"] == "429" for h in hops)
    assert any(h["outcome"] == "200" for h in hops)


# -- (d) cross-hop stitching --------------------------------------------------


def test_stitched_trace_for_failed_over_request(engine, fleet_factory,
                                                monkeypatch):
    arrays, _ = engine
    fleet = fleet_factory(n=2)
    monkeypatch.setenv("REPORTER_FAULT_ROUTER_CONNECT", "refused:1")
    st, hd, _b = post_json(fleet.url + "/report",
                           street_trace(arrays, "veh-stitch"))
    assert st == 200
    tid = hd["X-Reporter-Trace"]
    st, out = get_json(fleet.url + "/debug/traces?id=%s" % tid)
    assert st == 200
    stitched = out["stitched"]
    hops = stitched["hops"]
    # >= 2 dispatch-attempt hop spans: the refused primary + the winner
    assert len([h for h in hops if h["span"] == "dispatch"]) >= 2
    assert any("error" in h["outcome"] for h in hops)
    assert any(h["outcome"] == "200" for h in hops)
    assert stitched["attempts"] >= 2
    # the replica's span tree is spliced under the router's (the winning
    # leg carried X-Reporter-Flight-Keep, so the replica side is pinned
    # by the flight recorder — retention is guaranteed, not sampled)
    children = stitched["children"]
    assert children and any(e.get("endpoint") == "report"
                            for e in children)
    assert all(e["trace_id"] == tid for e in children)
    assert any(e.get("flight_keep") == "failover" for e in children)
    # router residency + ranking marks ride the router entry
    assert "total_s" in stitched["timings"]
    assert "ranking_s" in stitched["timings"]


def test_stitched_hedge_marks_cancelled_leg(engine, fleet_factory,
                                            monkeypatch):
    arrays, _ = engine
    fleet = fleet_factory(n=2, router_kw={"hedge_ms": 100.0})
    monkeypatch.setenv("REPORTER_FAULT_REPLICA_SLOW_ACCEPT", "1.2:1")
    st, hd, _b = post_json(fleet.url + "/report",
                           street_trace(arrays, "veh-hedge"))
    assert st == 200
    tid = hd["X-Reporter-Trace"]
    st, out = get_json(fleet.url + "/debug/traces?id=%s" % tid)
    assert st == 200
    hops = out["stitched"]["hops"]
    assert any(h["span"] == "hedge" and h["outcome"] == "200"
               for h in hops)
    assert any(h.get("cancelled") for h in hops)


def test_trace_by_id_on_replica_and_404(engine, fleet_factory):
    arrays, _ = engine
    fleet = fleet_factory(n=2)
    st, hd, _b = post_json(
        fleet.url + "/report", street_trace(arrays, "veh-byid"),
        headers={"X-Reporter-Flight-Keep": "test"})
    assert st == 200
    tid = hd["X-Reporter-Trace"]
    rid = hd["X-Reporter-Replica"]
    rep = fleet.by_id(rid)
    st, out = get_json(rep.url + "/debug/traces?id=%s" % tid)
    assert st == 200 and out["trace_id"] == tid
    assert out["traces"] and out["traces"][0]["trace_id"] == tid
    assert out["traces"][0]["flight_keep"] == "test"
    st, out = get_json(rep.url + "/debug/traces?id=no-such-trace")
    assert st == 404 and out["traces"] == []
    st, out = get_json(fleet.url + "/debug/traces?id=no-such-trace")
    assert st == 404


# -- (e) per-replica debug selector -------------------------------------------


def test_router_debug_replica_selector(engine, fleet_factory):
    arrays, _ = engine
    fleet = fleet_factory(n=2)
    st, out = get_json(fleet.url + "/debug/attrib")
    assert st == 400 and set(out["replicas"]) == {"fed-rep-0", "fed-rep-1"}
    st, out = get_json(fleet.url + "/debug/attrib?replica=nope")
    assert st == 404 and "fed-rep-0" in out["replicas"]
    st, out = get_json(fleet.url + "/debug/attrib?replica=fed-rep-0")
    assert st == 200 and "summary" in out
    # profile passes the replica's answer through verbatim (cpu backend
    # answers 501; the single-flight 409 contract rides the same path)
    st, out = get_json(fleet.url + "/debug/profile?replica=fed-rep-1")
    assert st == 501 and "jax backend" in out["error"]


# -- flight-recorder dump paths (unit half of the collision satellite) --------


def test_flight_dump_name_embeds_replica_id(monkeypatch, tmp_path):
    monkeypatch.setenv("REPORTER_REPLICA_ID", "rep/odd id")
    name = obs_flight.default_dump_name()
    assert name.startswith("reporter_flight_rep_odd_id_")
    monkeypatch.delenv("REPORTER_REPLICA_ID")
    assert obs_flight.default_dump_name() == \
        "reporter_flight_%d.json" % os.getpid()
    # a directory dump path gets the replica-qualified name inside it
    monkeypatch.setenv("REPORTER_REPLICA_ID", "rep-9")
    rec = obs_flight.FlightRecorder(capacity=4, slow_ms=0)
    from reporter_tpu.obs.trace import Span

    span = Span("t")
    span.finish()
    rec.record(span)
    out = rec.dump(str(tmp_path))
    assert out is not None
    assert os.path.basename(out).startswith("reporter_flight_rep-9_")
    assert json.load(open(out))["traces"]


def test_pinned_flight_decision():
    from reporter_tpu.obs.trace import Span

    rec = obs_flight.FlightRecorder(capacity=8, slow_ms=10_000,
                                    sample_every=1_000_000)
    span = Span("t")
    span.meta["flight_keep"] = "failover"
    span.finish()
    assert rec.record(span) == "pinned"
    plain = Span("t2")
    plain.finish()
    assert rec.record(plain) == "dropped"
    assert rec.find(span.trace_id)[0]["flight_keep"] == "failover"


# -- (f) consistency invariant + dump collisions over real processes ----------


def test_subprocess_fleet_consistency_and_dump_isolation(engine, tmp_path):
    """Two REAL serve processes behind an in-proc router: (1) the sum of
    the federated per-replica ``reporter_requests_total`` counters equals
    the client-observed request count, and the per-replica split matches
    the X-Reporter-Replica echoes exactly; (2) both processes share ONE
    dump dir and their SIGTERM flight dumps land in distinct
    replica-tagged files."""
    arrays, _ = engine
    conf = {
        "network": {"type": "grid", "rows": 5, "cols": 5,
                    "spacing_m": 150.0},
        "matcher": {"search_radius": 50.0},
        "backend": "cpu",
        "batch": {"max_batch": 64, "max_wait_ms": 2},
        "warmup": False,
    }
    conf_path = tmp_path / "config.json"
    conf_path.write_text(json.dumps(conf))
    dump_dir = tmp_path / "dumps"
    dump_dir.mkdir()
    procs = []
    urls = []
    try:
        for i in range(2):
            env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
                       REPORTER_REPLICA_ID="sub-rep-%d" % i,
                       REPORTER_FLIGHT_DUMP=str(dump_dir),
                       REPORTER_FLIGHT_SLOW_MS="0",  # retain everything
                       REPORTER_DRAIN_GRACE_S="10")
            p = subprocess.Popen(
                [sys.executable, "-m", "reporter_tpu.serve",
                 str(conf_path), "127.0.0.1:0"],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
            procs.append(p)
        for p in procs:
            port = None
            deadline = time.monotonic() + 90
            while time.monotonic() < deadline and port is None:
                line = p.stdout.readline()
                if not line:
                    time.sleep(0.05)
                    continue
                if b"service on 127.0.0.1:" in line:
                    port = int(line.split(b"127.0.0.1:")[1].split()[0])
            assert port, "no bind line from replica"
            urls.append("http://127.0.0.1:%d" % port)
        for u in urls:
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                try:
                    st, h = get_json(u + "/health", timeout=2)
                    if st == 200 and h.get("backend"):
                        break
                except Exception:  # noqa: BLE001 - still booting
                    pass
                time.sleep(0.25)
            else:
                pytest.fail("replica never became healthy")

        router = FleetRouter(urls, probe_interval_s=0.2)
        router.start()
        httpd = router.make_server("127.0.0.1", 0)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        rurl = "http://127.0.0.1:%d" % httpd.server_port
        try:
            observed = {}
            n = 14
            for k in range(n):
                st, hd, _b = post_json(
                    rurl + "/report", street_trace(arrays, "veh-%d" % k))
                assert st == 200
                rid = hd["X-Reporter-Replica"]
                observed[rid] = observed.get(rid, 0) + 1
            assert set(observed) == {"sub-rep-0", "sub-rep-1"}

            st, text = get_raw(rurl + "/metrics?pull=1")
            m = parse_metrics(text)
            federated = {}
            for lv, v in m["reporter_requests_total"].items():
                d = dict(lv)
                # only the replica-labeled federated samples: the router
                # process's own registry renders this family too (it
                # imports serve/service.py), sample-bearing here only
                # because THIS test process ran in-proc fleets earlier
                if "replica" in d and d.get("endpoint") == "report":
                    federated[d["replica"]] = \
                        federated.get(d["replica"], 0) + int(v)
            shed = m.get("reporter_router_shed_total", {}).get((), 0)
            # the invariant: nothing counted twice, nothing lost
            assert sum(federated.values()) + int(shed) == n
            assert federated == observed
        finally:
            httpd.shutdown()
            httpd.server_close()
            router.stop()

        # SIGTERM both: the dumps land in the SHARED dir under distinct
        # replica-tagged names
        for p in procs:
            p.send_signal(signal.SIGTERM)
        for p in procs:
            assert p.wait(timeout=30) == 0
        dumps = sorted(f.name for f in dump_dir.iterdir())
        assert len(dumps) == 2, dumps
        assert dumps[0].startswith("reporter_flight_sub-rep-0_")
        assert dumps[1].startswith("reporter_flight_sub-rep-1_")
        for f in dump_dir.iterdir():
            assert json.load(open(f))["traces"]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
