"""Hot/cold tiered UBODT differentials (docs/performance.md
"Continent-scale data plane"): match output must be BIT-IDENTICAL to the
untiered table for every tier state — both viterbi kernels, both table
layouts, cold-miss storms, eviction churn mid-stream, a hot arena smaller
than one bucket row, and tier state across UBODT.relayout()."""

import dataclasses
import json

import numpy as np
import pytest

from reporter_tpu.matching import MatcherConfig, SegmentMatcher
from reporter_tpu.tiles.arrays import build_graph_arrays
from reporter_tpu.tiles.network import grid_city
from reporter_tpu.tiles.tiering import (
    TieredTable, parse_shard, shard_bucket_range,
)
from reporter_tpu.tiles.ubodt import build_ubodt


@pytest.fixture(scope="module")
def setup():
    city = grid_city(rows=5, cols=5, spacing_m=150.0)
    arrays = build_graph_arrays(city, cell_size=100.0)
    return city, arrays


@pytest.fixture(scope="module")
def tables(setup):
    _, arrays = setup
    return {layout: build_ubodt(arrays, delta=1500.0, layout=layout)
            for layout in ("cuckoo", "wide32")}


def fleet_traces(arrays, n=10, pts=12, seed=3):
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        r = int(rng.integers(0, 5))
        row_nodes = [r * 5 + c for c in range(5)]
        xs = arrays.node_x[row_nodes]
        ys = arrays.node_y[row_nodes]
        t = np.linspace(0.05, 0.9, pts)
        px = np.interp(t, np.linspace(0, 1, 5), xs) + rng.normal(0, 3, pts)
        py = np.interp(t, np.linspace(0, 1, 5), ys) + rng.normal(0, 3, pts)
        lat, lon = arrays.proj.to_latlon(px, py)
        out.append({"uuid": "v%d" % i, "trace": [
            {"lat": float(a), "lon": float(o), "time": 1000.0 + 15 * j}
            for j, (a, o) in enumerate(zip(lat, lon))]})
    return out


# -- ops-level probe differential -------------------------------------------


@pytest.mark.parametrize("layout", ["cuckoo", "wide32"])
@pytest.mark.parametrize("hot_bytes", [1, 3000, 1 << 30])
def test_probe_bit_identical(tables, layout, hot_bytes):
    """jit / vmap / dedup probe paths over every tier occupancy: empty
    arena (budget below one row), partial, and everything-hot."""
    import jax
    import jax.numpy as jnp

    from reporter_tpu.ops.hashtable import ubodt_lookup

    u = tables[layout]
    du = u.to_device()
    rng = np.random.default_rng(7)
    src = jnp.asarray(rng.integers(0, 30, size=(16, 5, 4)), jnp.int32)
    dst = jnp.asarray(rng.integers(0, 30, size=(16, 5, 4)), jnp.int32)
    want = jax.jit(ubodt_lookup)(du, src, dst)
    tier = TieredTable(u, hot_bytes)
    tdu = tier.device()
    for _ in range(2):  # cold storm, then the EWMA-warmed arena
        got = jax.jit(ubodt_lookup)(tdu, src, dst)
        for a, b in zip(want, got):
            assert (np.asarray(a) == np.asarray(b)).all()
        tier.maintain()
    # dedup path (the other lax.cond fallback composes with this one)
    got = jax.jit(
        lambda u_, s_, d_: ubodt_lookup(u_, s_, d_, dedup=True))(
            tdu, src, dst)
    for a, b in zip(want, got):
        assert (np.asarray(a) == np.asarray(b)).all()
    # under vmap: the carry/session seam-transition context (cond lowers
    # to a select; both sides still produce identical bytes)
    vm = jax.jit(jax.vmap(ubodt_lookup, in_axes=(None, 0, 0)))
    for a, b in zip(vm(du, src, dst), vm(tdu, src, dst)):
        assert (np.asarray(a) == np.asarray(b)).all()


def test_cold_miss_storm_counters(tables):
    import jax
    import jax.numpy as jnp

    from reporter_tpu.ops.hashtable import ubodt_lookup
    from reporter_tpu.tiles import tiering

    u = tables["cuckoo"]
    tier = TieredTable(u, 4096, maintain_every=1)
    tdu = tier.device()
    h0 = tiering.C_TIER_HITS.value
    m0 = tiering.C_TIER_MISSES.value
    rng = np.random.default_rng(11)
    src = jnp.asarray(rng.integers(0, 25, size=(256,)), jnp.int32)
    dst = jnp.asarray(rng.integers(0, 25, size=(256,)), jnp.int32)
    jax.block_until_ready(jax.jit(ubodt_lookup)(tdu, src, dst))
    tier.drain_stats()
    assert tiering.C_TIER_MISSES.value > m0  # everything cold at boot
    assert tiering.C_TIER_HITS.value >= h0
    # the EWMA admitted the stormed buckets: repeat traffic now hits
    tier.maintain()
    h1 = tiering.C_TIER_HITS.value
    jax.block_until_ready(jax.jit(ubodt_lookup)(tdu, src, dst))
    tier.drain_stats()
    assert tiering.C_TIER_HITS.value > h1


# -- matcher-level wire differential ----------------------------------------


@pytest.mark.parametrize("layout", ["cuckoo", "wide32"])
@pytest.mark.parametrize("kernel", [
    "scan", pytest.param("assoc", marks=pytest.mark.slow)])
def test_match_wire_identical(setup, tables, layout, kernel):
    """Full matcher: bucketed + carry-chain traffic, tiered (tiny hot
    budget) vs untiered, wire-identical; eviction churn mid-stream stays
    identical."""
    _, arrays = setup
    cfg = MatcherConfig(ubodt_layout=layout, viterbi_kernel=kernel,
                        probe_dedup=True, length_buckets=[16])
    u = tables[layout]
    base = SegmentMatcher(arrays=arrays, ubodt=u, config=cfg)
    trs = fleet_traces(arrays) + fleet_traces(arrays, n=1, pts=40, seed=9)
    want = base.match_many(trs)
    tiered = SegmentMatcher(
        arrays=arrays, ubodt=u,
        config=dataclasses.replace(cfg, ubodt_hot_bytes=4096))
    assert tiered.tiering is not None
    assert tiered.tiering.table_bytes > 4 * 4096  # a genuinely cold table
    got = tiered.match_many(trs)
    assert json.dumps(want, sort_keys=True) == json.dumps(got,
                                                          sort_keys=True)
    # eviction churn mid-stream: hammer a different traffic mix, force
    # maintenance, then replay the original — still wire-identical
    tiered.match_many(fleet_traces(arrays, n=8, seed=77))
    ev = tiered.tiering.maintain()
    tiered.tiering.maintain()
    got2 = tiered.match_many(trs)
    assert json.dumps(want, sort_keys=True) == json.dumps(got2,
                                                          sort_keys=True)
    assert ev["hot_rows"] > 0


def test_session_step_identical(setup, tables):
    """The per-vehicle session step (carry round trip included) is
    bit-exact across tiering — the streaming path probes through the
    same seam."""
    _, arrays = setup
    cfg = MatcherConfig(length_buckets=[16])
    base = SegmentMatcher(arrays=arrays, ubodt=tables["cuckoo"],
                          config=cfg)
    tiered = SegmentMatcher(
        arrays=arrays, ubodt=tables["cuckoo"],
        config=dataclasses.replace(cfg, ubodt_hot_bytes=2048))
    tr = fleet_traces(arrays, n=1, pts=6)[0]
    items = [{"points": tr["trace"][:3], "carry": None,
              "t0": float(tr["trace"][0]["time"]), "pkey": ()}]
    (rec_a, aux_a, carry_a), = base.match_sessions(items)
    (rec_b, aux_b, carry_b), = tiered.match_sessions(items)
    for a, b in zip(rec_a, rec_b):
        assert (np.asarray(a) == np.asarray(b)).all()
    for k in ("scores", "edge", "offset"):
        assert (np.asarray(carry_a[k]) == np.asarray(carry_b[k])).all()
    # step 2 from the carried beam
    items2 = [{"points": tr["trace"][3:], "carry": carry_a,
               "t0": float(tr["trace"][0]["time"]), "pkey": ()}]
    (rec_a2, _, _), = base.match_sessions(items2)
    items2[0]["carry"] = carry_b
    (rec_b2, _, _), = tiered.match_sessions(items2)
    for a, b in zip(rec_a2, rec_b2):
        assert (np.asarray(a) == np.asarray(b)).all()


# -- tier mechanics ---------------------------------------------------------


def test_hot_arena_smaller_than_one_row(tables):
    """A budget below one bucket row is legal: capacity 0, everything
    pages cold, residency 0."""
    tier = TieredTable(tables["wide32"], 1)
    assert tier.capacity == 0
    assert tier.summary()["hot_rows"] == 0
    assert tier.maintain() == {"hot_rows": 0, "admitted": 0, "evicted": 0}


def test_eviction_accounting(tables):
    from reporter_tpu.tiles import tiering

    u = tables["cuckoo"]
    tier = TieredTable(u, 8 * 512)  # 8 cuckoo rows
    assert tier.capacity == 8
    # synthesise skewed probe traffic directly through the stats hook
    tier._note(np.arange(8), np.zeros(8, bool))
    tier.drain_stats()
    tier.maintain()
    assert set(tier.hot_buckets()) >= set(range(8))
    e0 = tiering.C_TIER_EVICTIONS.value
    # a hotter competing set must displace the old one
    rival = np.arange(tier.n_buckets - 8, tier.n_buckets)
    for _ in range(6):
        tier._note(np.repeat(rival, 4), np.zeros(32, bool))
        tier.drain_stats()
        tier.maintain()
    assert set(tier.hot_buckets()) == set(rival)
    assert tiering.C_TIER_EVICTIONS.value > e0


def test_tier_state_across_relayout(setup, tables):
    """UBODT.relayout() composes with tiering two ways: re-tiering the
    relayouted table directly, and the matcher's env-driven relayout of
    a prebuilt table — both still bit-identical to untiered."""
    _, arrays = setup
    u = tables["cuckoo"]
    wide = u.relayout("wide32")
    tier = TieredTable(wide, 4096)
    assert tier.n_buckets == wide.n_buckets
    assert tier.lanes == 256
    cfg = MatcherConfig(ubodt_layout="wide32", ubodt_hot_bytes=4096,
                        length_buckets=[16])
    m = SegmentMatcher(arrays=arrays, ubodt=u, config=cfg)  # relayouts
    assert m.ubodt.layout == "wide32"
    assert m.tiering is not None
    assert m.tiering.ubodt.layout == "wide32"
    base = SegmentMatcher(
        arrays=arrays, ubodt=wide,
        config=MatcherConfig(ubodt_layout="wide32", length_buckets=[16]))
    trs = fleet_traces(arrays, n=4)
    assert json.dumps(base.match_many(trs), sort_keys=True) == \
        json.dumps(m.match_many(trs), sort_keys=True)


def test_shard_seeding_and_parse(tables):
    u = tables["cuckoo"]
    lo, hi = shard_bucket_range(1, 4, u.n_buckets)
    tier = TieredTable(u, 4 * 512, shard=(1, 4))
    hot = tier.hot_buckets()
    assert len(hot) == 4
    assert (hot >= lo).all() and (hot < hi).all()
    # the seed survives a zero-traffic maintenance pass (never evict a
    # probed-nothing world into a different probed-nothing world)
    tier.maintain()
    assert set(tier.hot_buckets()) == set(hot)
    assert parse_shard("") is None
    assert parse_shard("2/8") == (2, 8)
    with pytest.raises(ValueError):
        parse_shard("8/2")
    with pytest.raises(ValueError):
        parse_shard("nope")
    # the partition tiles the bucket space exactly
    spans = [shard_bucket_range(i, 3, u.n_buckets) for i in range(3)]
    assert spans[0][0] == 0 and spans[-1][1] == u.n_buckets
    assert all(a[1] == b[0] for a, b in zip(spans, spans[1:]))


@pytest.mark.parametrize("devices,graph_devices", [
    pytest.param(2, 1, marks=pytest.mark.slow), (8, 4)])
def test_mesh_composes_tiering(setup, tables, devices, graph_devices):
    """Tiering and the dp/gp mesh COMPOSE (docs/performance.md "One
    logical matcher per pod"): the hot-bucket arena shards by the same
    contiguous-bucket partition the gp probe uses, hot_bytes is a
    per-chip budget, and the meshed+tiered wire output stays
    bit-identical to the untiered single-device matcher."""
    import jax

    _, arrays = setup
    if len(jax.devices()) < devices:
        pytest.skip("needs >= %d devices for the mesh" % devices)
    cfg = MatcherConfig(devices=devices, graph_devices=graph_devices,
                        ubodt_hot_bytes=4096, length_buckets=[16])
    m = SegmentMatcher(arrays=arrays, ubodt=tables["cuckoo"], config=cfg)
    assert m.tiering is not None
    ts = m.tiering.summary()
    # per-chip budget: gp ranks multiply the resident set
    assert ts.get("hot_bytes_total", ts["hot_bytes"]) \
        == ts["hot_bytes"] * graph_devices
    base = SegmentMatcher(arrays=arrays, ubodt=tables["cuckoo"],
                          config=MatcherConfig(length_buckets=[16]))
    trs = fleet_traces(arrays, n=6)
    assert json.dumps(m.match_many(trs), sort_keys=True) == \
        json.dumps(base.match_many(trs), sort_keys=True)
    # churn the tier mid-stream and replay: still bit-identical
    m.tiering.maintain()
    assert json.dumps(m.match_many(trs), sort_keys=True) == \
        json.dumps(base.match_many(trs), sort_keys=True)
