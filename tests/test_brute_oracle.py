"""Triple-agreement test: device kernel == CPU oracle == brute-force matcher.

The CPU oracle deliberately mirrors the device's candidate machinery
(f32 cell math, pool truncation, UBODT delta bound) for byte-exact
diffing, which blinds the backend diff to any bug in a SHARED rule.  The
brute matcher (baseline/brute_matcher.py) shares none of it: exhaustive
f64 candidates over every edge, exact unbounded Dijkstra per probe, f64
scoring.  All three must produce the same wire output on tiny fixtures
across >= 3 topologies (VERDICT r05 next #9).

Fixture discipline: traces follow roads with small noise and the
exhaustive candidate count per point is asserted <= beam_k, so the
device's K-beam and the brute pool see the same candidate sets — the
agreement then tests the RULES (transition cuts, jitter handling, breaks,
backtrace), not pool-truncation artifacts.
"""

import numpy as np
import pytest

from reporter_tpu.baseline.brute_matcher import BruteForceMatcher
from reporter_tpu.matching import MatcherConfig, SegmentMatcher
from reporter_tpu.tiles.arrays import build_graph_arrays
from reporter_tpu.tiles.network import Edge, RoadNetwork, grid_city
from reporter_tpu.tiles.segment_id import pack_segment_id
from reporter_tpu.tiles.ubodt import build_ubodt

LAT0, LON0 = 37.75, -122.45


def _line_network() -> RoadNetwork:
    """Four nodes in a dogleg line, all two-way."""
    net = RoadNetwork()
    pts = [(0.0, 0.0), (0.0, 0.002), (0.0012, 0.0035), (0.0012, 0.0055)]
    for dlat, dlon in pts:
        net.add_node(LAT0 + dlat, LON0 + dlon)
    sid = 1
    for a in range(3):
        net.add_road(a, a + 1, level=0, speed_kph=50.0,
                     segment_id=pack_segment_id(0, 7, sid),
                     rev_segment_id=pack_segment_id(0, 7, sid + 1),
                     way_id=sid)
        sid += 2
    return net


def _oneway_loop_network() -> RoadNetwork:
    """A T-junction with a one-way spur: asymmetric reachability, so a
    wrong-direction match must pay a real loop route."""
    net = RoadNetwork()
    pts = [(0.0, 0.0), (0.0, 0.003), (0.0, 0.006), (0.0025, 0.003)]
    for dlat, dlon in pts:
        net.add_node(LAT0 + dlat, LON0 + dlon)
    sid = 1
    for a, b in ((0, 1), (1, 2)):
        net.add_road(a, b, level=0, speed_kph=50.0,
                     segment_id=pack_segment_id(0, 7, sid),
                     rev_segment_id=pack_segment_id(0, 7, sid + 1),
                     way_id=sid)
        sid += 2
    # the spur is one-way AWAY from the junction
    net.add_edge(Edge(1, 3, level=1, speed_kph=40.0,
                      segment_id=pack_segment_id(1, 7, sid), way_id=sid))
    return net


def _road_trace(net, uid, n_pts=12, edge_idx=0, jitter=2e-5, seed=0):
    rng = np.random.default_rng(seed)
    e = net.edges[edge_idx]
    sh = np.asarray(e.shape, float)
    f = np.linspace(0, 1, n_pts)
    lat = np.interp(f, np.linspace(0, 1, len(sh)), sh[:, 0])
    lon = np.interp(f, np.linspace(0, 1, len(sh)), sh[:, 1])
    lat = lat + rng.normal(0, jitter, n_pts)
    lon = lon + rng.normal(0, jitter, n_pts)
    return {
        "uuid": uid,
        "match_options": {"mode": "auto", "report_levels": [0, 1, 2],
                          "transition_levels": [0, 1, 2]},
        "trace": [{"lat": float(a), "lon": float(o),
                   "time": 1000 + 5 * i, "accuracy": 5}
                  for i, (a, o) in enumerate(zip(lat, lon))],
    }


TOPOLOGIES = {
    "grid": lambda: grid_city(rows=3, cols=3, spacing_m=220.0),
    "line": _line_network,
    "oneway": _oneway_loop_network,
}


@pytest.mark.parametrize("topo", sorted(TOPOLOGIES))
def test_triple_agreement(topo):
    net = TOPOLOGIES[topo]()
    arrays = build_graph_arrays(net, cell_size=100.0)
    # delta large enough that the UBODT covers the whole fixture: the
    # brute matcher routes unbounded, so truncation must never bind
    ubodt = build_ubodt(arrays, delta=20000.0)
    cfg = MatcherConfig(ubodt_delta=20000.0)
    mjax = SegmentMatcher(arrays=arrays, ubodt=ubodt, config=cfg)
    mcpu = SegmentMatcher(arrays=arrays, ubodt=ubodt, config=cfg,
                          backend="cpu")
    brute = BruteForceMatcher(arrays, cfg)

    n_edges = net.num_edges
    traces = [
        _road_trace(net, "%s-0" % topo, edge_idx=0, seed=1),
        _road_trace(net, "%s-1" % topo, edge_idx=min(2, n_edges - 1), seed=2),
        _road_trace(net, "%s-2" % topo, edge_idx=min(4, n_edges - 1),
                    n_pts=16, seed=3),
    ]

    # precondition: the exhaustive pool fits the device beam, so all three
    # matchers consider identical candidate sets
    idxs = list(range(len(traces)))
    T = max(len(t["trace"]) for t in traces)
    px, py, tm, valid, times = mjax._fill_rows(traces, idxs, T)
    for b in range(len(traces)):
        n = int(valid[b].sum())
        counts = brute.candidate_counts(px[b, :n], py[b, :n])
        assert max(counts) <= cfg.beam_k, (topo, b, counts)
        assert min(counts) >= 1, (topo, b, counts)

    out_jax = mjax.match_many(traces)
    out_cpu = mcpu.match_many(traces)

    # brute results through the SAME association layer (the independence
    # target is the matching rules; association parity has its own suite)
    edge, offset, breaks = brute.run_batch(px, py, tm, valid)
    out_brute = [None] * len(traces)
    mjax._associate_and_store(idxs, edge, offset, breaks, times, out_brute)

    for i in range(len(traces)):
        assert out_jax[i] == out_cpu[i], (topo, i)
        assert out_jax[i] == out_brute[i], (topo, i)


def test_brute_breaks_on_teleport():
    """A teleporting trace must break identically in all three matchers —
    the break/restart rule is the semantics most entangled with the shared
    NEG_INF liveness convention."""
    net = TOPOLOGIES["grid"]()
    arrays = build_graph_arrays(net, cell_size=100.0)
    ubodt = build_ubodt(arrays, delta=20000.0)
    cfg = MatcherConfig(ubodt_delta=20000.0)
    mjax = SegmentMatcher(arrays=arrays, ubodt=ubodt, config=cfg)
    mcpu = SegmentMatcher(arrays=arrays, ubodt=ubodt, config=cfg,
                          backend="cpu")
    brute = BruteForceMatcher(arrays, cfg)

    tr = _road_trace(net, "teleport", n_pts=12, edge_idx=0, seed=5)
    for p in tr["trace"][6:]:  # ~4.4 km jump mid-trace
        p["lat"] += 0.04
    traces = [tr]
    idxs = [0]
    px, py, tm, valid, times = mjax._fill_rows(traces, idxs, 12)
    edge, offset, breaks = brute.run_batch(px, py, tm, valid)
    assert bool(breaks[0, 6]), "brute must break at the teleport"
    out_brute = [None]
    mjax._associate_and_store(idxs, edge, offset, breaks, times, out_brute)
    out_jax = mjax.match_many(traces)
    out_cpu = mcpu.match_many(traces)
    assert out_jax[0] == out_cpu[0] == out_brute[0]
