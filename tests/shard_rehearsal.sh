#!/usr/bin/env bash
# Continent-scale data-plane gating rehearsal (the CI `shard-rehearsal`
# leg; runnable locally): tools/fleet.py boots 3 warmed replicas, each
# holding one UBODT shard assignment (REPORTER_UBODT_SHARD=i/3) and a
# hot-bucket arena budget (REPORTER_UBODT_HOT_BYTES) a small fraction of
# the table — the fleet as a whole serves a table >4x ANY single
# replica's hot budget, host-paging the cold rows — behind the router
# with the flag-gated geo-aware ranking term ON.  The verdict:
#
#   1. loadgen SLO verdict green (rc 0) over the whole run: the tiered,
#      sharded fleet serves real traffic inside its objectives
#   2. the table really exceeded the budget: /statusz ubodt_tier shows
#      table_bytes >= 4 * hot_bytes on every replica
#   3. the tiers actually worked: federated /metrics counts
#      reporter_ubodt_tier_hits_total > 0 AND _misses_total > 0, and
#      every replica's residency gauge is > 0 (arena seeded + admitting)
#   4. the geo term really ranked: reporter_router_geo_requests_total
#      counted every proxied report, and bodies without coordinates did
#      not break routing
#
# Usage: tests/shard_rehearsal.sh [workdir]
set -euo pipefail

. "$(dirname "$0")/rehearsal_lib.sh"
export REPORTER_RETRY_BASE_S="${REPORTER_RETRY_BASE_S:-0.05}"
export REPORTER_ROUTER_PROBE_S="${REPORTER_ROUTER_PROBE_S:-0.25}"
# the continent-scale knobs under test
export REPORTER_UBODT_HOT_BYTES="${REPORTER_UBODT_HOT_BYTES:-16384}"
export REPORTER_ROUTER_GEO=1
# ~220 m cells over the synthetic city so the geo term sees several cells
export REPORTER_ROUTER_GEO_CELL_DEG=0.002
# serving objectives (loose: correctness of the data plane is the gate,
# not CPU latency)
export REPORTER_SLO_AVAILABILITY=0.95
export REPORTER_SLO_P99_MS=8000
export REPORTER_SLO_P999_MS=0
export REPORTER_SLO_DEGRADED_FRAC=0
reh_init "${1:-}" reporter-shard
export REPORTER_XLA_CACHE_DIR="$WORK/xla-cache"
ROUTER_PORT=18181
BASE_PORT=18182
echo "shard rehearsal workdir: $WORK (hot budget $REPORTER_UBODT_HOT_BYTES B)"

cat > "$WORK/config.json" <<EOF
{
  "network": {"type": "grid", "rows": 8, "cols": 8, "spacing_m": 200},
  "matcher": {"sigma_z": 4.07, "beta": 3.0, "search_radius": 50.0,
              "length_buckets": [16],
              "warmup_batch_sizes": [1, 4, 16]},
  "backend": "jax",
  "batch": {"max_batch": 64, "max_wait_ms": 5, "session_wait_ms": 2}
}
EOF

# ---- boot the sharded fleet ----------------------------------------------
python tools/fleet.py --config "$WORK/config.json" --replicas 3 \
    --base-port "$BASE_PORT" --router-port "$ROUTER_PORT" \
    --ubodt-shards 3 \
    --workdir "$WORK" --warmup --cpu-default --drain-grace 20 \
    > "$WORK/fleet.log" 2>&1 &
FLEET_PID=$!
reh_track_fleet "$FLEET_PID" "$WORK"

if ! reh_wait_fleet "http://127.0.0.1:$ROUTER_PORT" 3 "$BASE_PORT" 3 600 warmed; then
    echo "FAIL: fleet never reached 3 warmed replicas; fleet log tail:"
    tail -30 "$WORK/fleet.log"
    for f in "$WORK"/replica-*.log "$WORK"/router.log; do
        echo "--- $f"; tail -10 "$f" 2>/dev/null || true
    done
    exit 1
fi
echo "fleet up: 3 warmed replicas, one table shard + hot arena each"

# ---- drive real traffic through the router --------------------------------
python tools/loadgen.py --url "http://127.0.0.1:$ROUTER_PORT" \
    --rate 12 --duration 20 --vehicles 24 --points 48 --window 12 --grid 8 \
    --seed 5 --concurrency 16 --timeout-s 8 \
    --slo-availability 0.95 --slo-p99-ms 8000 \
    --out "$WORK/loadgen.json"
echo "loadgen SLO verdict: PASS (rc 0) against the tiered sharded fleet"

# ---- assertions -----------------------------------------------------------
python - "$WORK" "http://127.0.0.1:$ROUTER_PORT" "$BASE_PORT" <<'EOF'
import json, os, sys, urllib.request

work, router, base = sys.argv[1], sys.argv[2], int(sys.argv[3])
sys.path.insert(0, ".")
from reporter_tpu.obs.quantile import parse_metrics

def get(url):
    with urllib.request.urlopen(url, timeout=15) as f:
        return json.loads(f.read().decode())

hot_budget = int(os.environ["REPORTER_UBODT_HOT_BYTES"])

# 2. every replica's table really exceeds 4x its hot budget, the arena
# is seeded with its own shard, and the shard assignments tile 0..2
shards = set()
for i in range(3):
    sz = get("http://127.0.0.1:%d/statusz" % (base + i))
    tier = sz.get("ubodt_tier")
    assert tier, "replica %d serves untiered (ubodt_tier missing)" % i
    assert tier["hot_bytes"] == hot_budget, tier
    assert tier["table_bytes"] >= 4 * hot_budget, (
        "table %dB < 4x hot budget %dB on replica %d"
        % (tier["table_bytes"], hot_budget, i))
    assert tier["hot_rows"] > 0, tier
    assert tier["shard"] and tier["shard"].endswith("/3"), tier
    shards.add(tier["shard"])
print("tiered tables: %s, table >= 4x hot budget on all 3" % sorted(shards))
assert shards == {"0/3", "1/3", "2/3"}, shards

# 3. the tiers worked: federated hit AND miss counters counted, and the
# residency gauge is > 0 on every replica
with urllib.request.urlopen(router + "/metrics?pull=1", timeout=15) as f:
    m = parse_metrics(f.read().decode())

def fleet_sum(name):
    return sum(v for lv, v in m.get(name, {}).items()
               if "replica" in dict(lv))

hits = fleet_sum("reporter_ubodt_tier_hits_total")
misses = fleet_sum("reporter_ubodt_tier_misses_total")
assert hits > 0, "no hot-arena hits counted fleet-wide"
assert misses > 0, "no cold misses counted — the table never paged"
res = {dict(lv)["replica"]: v
       for lv, v in m.get("reporter_ubodt_tier_resident_rows", {}).items()
       if "replica" in dict(lv)}
assert len(res) == 3 and all(v > 0 for v in res.values()), res
print("tier counters: %d hits / %d misses fleet-wide, residency %r"
      % (hits, misses, res))

# 4. the geo-aware term ranked real requests
geo = {dict(lv).get("outcome"): v
       for lv, v in m.get("reporter_router_geo_requests_total", {}).items()}
assert sum(geo.values()) > 0, "geo ranking never engaged: %r" % geo
print("geo ranking engaged on %d requests (%r)"
      % (int(sum(geo.values())), geo))

art = json.load(open(work + "/loadgen.json"))
q = art.get("quantiles") or {}
p99 = q.get("p99") or q.get("0.99")
print("shard rehearsal PASS: %d requests%s"
      % (art.get("requests", 0),
         (", p99 %.0f ms" % (p99 * 1000.0)) if p99 else ""))
EOF

reh_stop_fleet
echo "shard rehearsal: PASS"
