"""Device-resident session arenas: the arena-on vs host-carry
differential suite (docs/performance.md "Device-resident session
arenas").

The bit-exact contract: with ``session_arena=True`` the carried Viterbi
beams live in a device slab (hot) / pinned_host pages (cold) and every
packed step is one donated in-place dispatch — yet the wire output, the
per-point records, and every seam (eviction churn mid-stream, an arena
smaller than the dispatch group, drain/handoff, checkpoint/restore,
``REPORTER_SESSION_ARENA=0``) stay BYTE-identical to the PR 12
host-carried path, across both viterbi kernels × both UBODT layouts ×
sparse on/off.
"""

import json

import numpy as np
import pytest

from reporter_tpu.matching import MatcherConfig, SegmentMatcher
from reporter_tpu.matching.session import (
    SessionCheckpointer, SessionEngine, SessionStore, read_checkpoints,
)
from reporter_tpu.synth import TraceSynthesizer
from reporter_tpu.tiles.arrays import build_graph_arrays
from reporter_tpu.tiles.network import grid_city
from reporter_tpu.tiles.ubodt import build_ubodt

MO = {"mode": "auto", "report_levels": [0, 1], "transition_levels": [0, 1]}
# one slot's exact payload: 12 bytes per beam entry + 17 fixed
SLOT_B = 12 * 8 + 17


@pytest.fixture(scope="module")
def setup():
    city = grid_city(rows=8, cols=8, spacing_m=150.0)
    arrays = build_graph_arrays(city, cell_size=100.0)
    ubodt = build_ubodt(arrays, delta=1500.0)
    return arrays, ubodt


def _matcher(setup, kernel="scan", **kw):
    arrays, ubodt = setup
    cfg = MatcherConfig(length_buckets=[16], session_buckets=[4, 16],
                        viterbi_kernel=kernel, **kw)
    return SegmentMatcher(arrays=arrays, ubodt=ubodt, config=cfg)


def _traces(arrays, b, t, seed=11, sigma=3.0):
    synth = TraceSynthesizer(arrays, seed=seed)
    return [s.trace for s in synth.batch(b, t, dt=5.0, sigma=sigma)]


def _engine(m, tail=512):
    store = SessionStore()
    return SessionEngine(m, store, tail_points=tail), store


def _stream_fleet(m, trs, step=2, batched=True):
    """Stream a fleet through a fresh engine; batched=True submits all
    vehicles per tick in one match_many (one dispatch group), False
    submits one vehicle at a time (round-robin — the churn shape for a
    tiny slab)."""
    eng, store = _engine(m)
    pts_max = max(len(t["trace"]) for t in trs)
    for j in range(0, pts_max, step):
        batch = [{"uuid": t["uuid"], "trace": t["trace"][j:j + step],
                  "match_options": MO}
                 for t in trs if t["trace"][j:j + step]]
        if batched:
            eng.match_many(batch)
        else:
            for item in batch:
                eng.match_many([item])
    return store


def _records(store, uuid):
    s = store.peek(uuid)
    return (np.array([r[0] for r in s.records], np.int64),
            np.array([r[1] for r in s.records], np.float32),
            np.array([r[2] for r in s.records], bool))


def _assert_store_equal(a, b, uuids):
    for u in uuids:
        ra, rb = _records(a, u), _records(b, u)
        for xa, xb, what in zip(ra, rb, ("edge", "offset", "break")):
            np.testing.assert_array_equal(xa, xb, err_msg="%s/%s" % (u, what))
    wa = {w["uuid"]: w["carry"] for w in a.export_all()}
    wb = {w["uuid"]: w["carry"] for w in b.export_all()}
    assert wa == wb  # exact f32 wire bytes, not approx


# -- the full differential grid ---------------------------------------------


@pytest.mark.parametrize("kernel", ["scan", "assoc"])
@pytest.mark.parametrize("layout", ["cuckoo", "wide32"])
def test_bitexact_vs_host_carry_kernels_layouts(setup, kernel, layout):
    arrays, _ = setup
    trs = _traces(arrays, 4, 10)
    kw = dict(kernel=kernel, ubodt_layout=layout)
    host = _stream_fleet(_matcher(setup, **kw), trs)
    m = _matcher(setup, session_arena=True, **kw)
    assert m.session_arena is not None
    arena = _stream_fleet(m, trs)
    _assert_store_equal(host, arena, [t["uuid"] for t in trs])


@pytest.mark.parametrize("kernel", ["scan", "assoc"])
def test_bitexact_sparse_on(setup, kernel):
    """Sparse cohorts ride the sparse arena twin program — still
    bit-identical to the sparse host-carry path."""
    arrays, _ = setup
    trs = _traces(arrays, 3, 10)
    kw = dict(kernel=kernel, sparse=True, sparse_gap_s=1.0)
    host = _stream_fleet(_matcher(setup, **kw), trs)
    arena = _stream_fleet(_matcher(setup, session_arena=True, **kw), trs)
    _assert_store_equal(host, arena, [t["uuid"] for t in trs])


@pytest.mark.parametrize("devices", [
    pytest.param(2, marks=pytest.mark.slow), 8])
def test_bitexact_mesh(setup, devices):
    """The dp-sharded slab (docs/performance.md "One logical matcher per
    pod"): arena-on over a mesh stays byte-identical to the 1-device
    host-carry reference — the slot axis shards, hot_slots rounds up to
    the dp width, and the gather/scatter reconstructs the global slab
    row-for-row."""
    import jax

    if len(jax.devices()) < devices:
        pytest.skip("needs >= %d virtual devices" % devices)
    arrays, _ = setup
    trs = _traces(arrays, 4, 10)
    host = _stream_fleet(_matcher(setup), trs)
    m = _matcher(setup, session_arena=True, devices=devices)
    assert m.session_arena is not None
    assert m.session_arena.hot_slots % devices == 0
    arena = _stream_fleet(m, trs)
    _assert_store_equal(host, arena, [t["uuid"] for t in trs])


def test_env_flag_reverts_bit_for_bit(setup, monkeypatch):
    """REPORTER_SESSION_ARENA=0 beats cfg.session_arena=True: no arena
    is built and the host-carry path runs (trivially bit-identical);
    =1 enables it over a default config."""
    arrays, _ = setup
    monkeypatch.setenv("REPORTER_SESSION_ARENA", "0")
    m_off = _matcher(setup, session_arena=True)
    assert m_off.session_arena is None
    monkeypatch.setenv("REPORTER_SESSION_ARENA", "1")
    m_on = _matcher(setup)
    assert m_on.session_arena is not None
    trs = _traces(arrays, 3, 8)
    _assert_store_equal(_stream_fleet(m_off, trs), _stream_fleet(m_on, trs),
                        [t["uuid"] for t in trs])


# -- tier seams --------------------------------------------------------------


def test_eviction_churn_midstream_bitexact(setup):
    """2 hot slots + 2 cold slots under 6 round-robin vehicles: every
    step promotes/demotes/spills, and the output never moves a bit."""
    arrays, _ = setup
    trs = _traces(arrays, 6, 10)
    host = _stream_fleet(_matcher(setup), trs, batched=False)
    m = _matcher(setup, session_arena=True,
                 session_arena_bytes=2 * SLOT_B,
                 session_arena_cold_bytes=2 * SLOT_B)
    arena = _stream_fleet(m, trs, batched=False)
    _assert_store_equal(host, arena, [t["uuid"] for t in trs])
    s = m.session_arena.summary()
    assert s["hot_slots"] == 2 and s["cold_slots"] == 2
    # the churn really happened
    assert s["promotions"] > 0 and s["evictions"] > 0 and s["readbacks"] > 0


def test_arena_smaller_than_dispatch_group_falls_back(setup):
    """A dispatch group wider than the whole hot slab cannot be slotted:
    the group rides the host-carry fallback (bit-identical), and the
    slab never admits it."""
    arrays, _ = setup
    trs = _traces(arrays, 5, 8)
    host = _stream_fleet(_matcher(setup), trs)
    m = _matcher(setup, session_arena=True, session_arena_bytes=1 * SLOT_B)
    assert m.session_arena.hot_slots == 1
    arena = _stream_fleet(m, trs)
    _assert_store_equal(host, arena, [t["uuid"] for t in trs])
    assert m.session_arena.summary()["promotions"] == 0


def test_steady_state_zero_readbacks(setup):
    """The zero-per-step-transfer invariant: streaming submits never
    read a beam back; only export does."""
    arrays, _ = setup
    m = _matcher(setup, session_arena=True)
    trs = _traces(arrays, 4, 12)
    eng, store = _engine(m)
    for j in range(0, 12, 2):
        eng.match_many([{"uuid": t["uuid"], "trace": t["trace"][j:j + 2],
                         "match_options": MO} for t in trs])
        assert m.session_arena.readbacks == 0
    store.export_all()
    assert m.session_arena.readbacks == len(trs)
    # hot residency is live and visible to the economics plane
    assert m.session_arena.tier_counts()["hot"] == len(trs)


def test_chain_over_bucket_bitexact(setup):
    """A submit beyond the largest session bucket chains through one
    arena slot in place — equal to the host-carried chain."""
    arrays, _ = setup
    trs = _traces(arrays, 2, 40, seed=7)
    host = _stream_fleet(_matcher(setup), trs, step=40)
    m = _matcher(setup, session_arena=True)
    arena = _stream_fleet(m, trs, step=40)
    _assert_store_equal(host, arena, [t["uuid"] for t in trs])


# -- drain / handoff / checkpoint seams --------------------------------------


def _stream_with_drain(m, trs, drain_at, drained):
    eng, store = _engine(m)
    popped = None
    for j in range(0, 12, 2):
        eng.match_many([{"uuid": t["uuid"], "trace": t["trace"][j:j + 2],
                         "match_options": MO}
                        for t in trs if t["trace"][j:j + 2]])
        if j == drain_at:
            popped = store.pop_wire(drained)
    return popped, store


def test_drain_popwire_midstream_bitexact(setup):
    """pop_wire (the SIGTERM drain's atomic export) mid-stream frees the
    slots and hands off EXACT beam bytes while the stayers keep
    streaming — under a churning tiny slab."""
    arrays, _ = setup
    trs = _traces(arrays, 4, 12)
    drained = [t["uuid"] for t in trs[:2]]
    stayers = [t["uuid"] for t in trs[2:]]
    p_host, s_host = _stream_with_drain(_matcher(setup), trs, 6, drained)
    m = _matcher(setup, session_arena=True)
    p_arena, s_arena = _stream_with_drain(m, trs, 6, drained)
    assert ([w["carry"] for w in p_host]
            == [w["carry"] for w in p_arena])
    # the drained beams WERE device-resident: the pop read them back
    assert m.session_arena.readbacks >= len(drained)
    _assert_store_equal(s_host, s_arena, stayers)


def test_handoff_racing_redispatched_point_bitexact(setup):
    """The PR 12 merge-on-conflict race with arena beams on BOTH sides:
    replica A drains a vehicle mid-stream, the router re-dispatches a
    point to replica B before the handoff lands, then the import merges
    — decode and ledger equal the host-carry twins running the same
    race."""
    arrays, _ = setup
    tr = _traces(arrays, 1, 12, seed=6)[0]
    cut = 8

    def race(m1, m2):
        eng1, store1 = _engine(m1)
        for j in range(cut):
            eng1.match_many([{"uuid": tr["uuid"],
                              "trace": [tr["trace"][j]],
                              "match_options": MO}])
        wire = json.loads(json.dumps(store1.pop_wire([tr["uuid"]])))
        eng2, store2 = _engine(m2)
        # the race loser: B already absorbed 2 points before the import
        eng2.match_many([{"uuid": tr["uuid"],
                          "trace": tr["trace"][cut:cut + 2],
                          "match_options": MO}])
        res = store2.import_wire(wire)
        assert res["merged"] == 1
        for j in range(cut + 2, 12):
            eng2.match_many([{"uuid": tr["uuid"],
                              "trace": [tr["trace"][j]],
                              "match_options": MO}])
        return store2

    s_host = race(_matcher(setup), _matcher(setup))
    s_arena = race(_matcher(setup, session_arena=True),
                   _matcher(setup, session_arena=True))
    _assert_store_equal(s_host, s_arena, [tr["uuid"]])
    assert s_arena.peek(tr["uuid"]).points_total == 12


def test_checkpoint_restore_seam_bitexact(setup, tmp_path):
    """The preemption arc with the arena on: checkpoint sweeps read back
    only touched slots (counted), a restored engine continues from the
    checkpoint wire bit-exactly vs the uninterrupted host twin."""
    arrays, _ = setup
    tr = _traces(arrays, 1, 12, seed=9)[0]
    ref = _stream_fleet(_matcher(setup), [tr], step=1)

    m = _matcher(setup, session_arena=True)
    eng, store = _engine(m)
    cp = SessionCheckpointer(store, str(tmp_path / "ckpt"),
                             cadence_s=3600.0, sync=False)
    for j in range(8):
        eng.match_many([{"uuid": tr["uuid"], "trace": [tr["trace"][j]],
                         "match_options": MO}])
    rb0 = m.session_arena.readbacks
    assert rb0 == 0  # streaming alone reads nothing back
    assert cp.sweep()["written"] == 1
    assert m.session_arena.readbacks == 1  # the checkpoint's slot read
    # the replica dies; an inheritor restores from the checkpoint dir
    wires = read_checkpoints(cp.dir)
    m2 = _matcher(setup, session_arena=True)
    eng2, store2 = _engine(m2)
    assert store2.import_wire(wires)["imported"] == 1
    for j in range(8, 12):
        eng2.match_many([{"uuid": tr["uuid"], "trace": [tr["trace"][j]],
                         "match_options": MO}])
    _assert_store_equal(ref, store2, [tr["uuid"]])


# -- the observable surface --------------------------------------------------


def test_summary_and_counters_shape(setup):
    """The /statusz session_arena block's contract: geometry, occupancy,
    and the three counters, all ints; tier_counts tracks residency."""
    arrays, _ = setup
    m = _matcher(setup, session_arena=True,
                 session_arena_bytes=3 * SLOT_B)
    trs = _traces(arrays, 2, 6)
    _stream_fleet(m, trs)
    s = m.session_arena.summary()
    for k in ("hot_slots", "hot_used", "cold_slots", "cold_used",
              "slot_bytes", "hot_bytes", "cold_bytes",
              "promotions", "evictions", "readbacks"):
        assert isinstance(s[k], int), k
    assert s["slot_bytes"] == SLOT_B and s["hot_slots"] == 3
    assert s["cold_memory_kind"] in ("pinned_host", "unpinned_host")
    t = m.session_arena.tier_counts()
    assert t["hot"] == s["hot_used"] and t["cold"] == s["cold_used"]
