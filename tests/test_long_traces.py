"""Chunked long-trace matching: fixed windows with carried Viterbi state.

A trace longer than the largest length bucket must stream through [B, W]
windows with state carried across boundaries — no HMM restart at the seams,
and results agreeing with a single-window match of the same trace.
"""

import numpy as np
import pytest

from reporter_tpu.matching import MatcherConfig, SegmentMatcher
from reporter_tpu.synth import TraceSynthesizer
from reporter_tpu.tiles.arrays import build_graph_arrays
from reporter_tpu.tiles.network import grid_city
from reporter_tpu.tiles.ubodt import build_ubodt


@pytest.fixture(scope="module")
def setup():
    city = grid_city(rows=8, cols=8, spacing_m=150.0)
    arrays = build_graph_arrays(city, cell_size=100.0)
    ubodt = build_ubodt(arrays, delta=1500.0)
    return arrays, ubodt


def _traces(arrays, B, T, seed=11, sigma=3.0):
    synth = TraceSynthesizer(arrays, seed=seed)
    return [s.trace for s in synth.batch(B, T, dt=5.0, sigma=sigma)]


def test_chunked_matches_single_window(setup):
    arrays, ubodt = setup
    T = 96
    traces = _traces(arrays, 3, T)

    # chunked: window 32 -> 3 chunks with carry
    cfg_small = MatcherConfig(length_buckets=[16, 32])
    m_small = SegmentMatcher(arrays=arrays, ubodt=ubodt, config=cfg_small)
    chunked = m_small.match_many(traces)

    # single window 128 fits the whole trace
    cfg_big = MatcherConfig(length_buckets=[128])
    m_big = SegmentMatcher(arrays=arrays, ubodt=ubodt, config=cfg_big)
    whole = m_big.match_many(traces)

    for c, w in zip(chunked, whole):
        ids_c = [r.get("segment_id") for r in c["segments"] if "segment_id" in r]
        ids_w = [r.get("segment_id") for r in w["segments"] if "segment_id" in r]
        assert ids_c, "chunked match produced no segments"
        # low-noise traces: the chunked decode must recover the same path
        assert ids_c == ids_w


def test_no_restart_at_window_boundary(setup):
    """The kernel must not raise an HMM break at chunk seams."""
    import jax.numpy as jnp

    from reporter_tpu.ops.viterbi import (
        MatchParams,
        initial_carry_batch,
        match_batch_carry,
    )

    arrays, ubodt = setup
    cfg = MatcherConfig()
    T, W = 64, 16
    traces = _traces(arrays, 2, T, seed=5, sigma=2.0)
    m = SegmentMatcher(arrays=arrays, ubodt=ubodt, config=cfg)
    px, py, tm, valid, _ = m._fill_rows(traces, [0, 1], T)

    p = MatchParams.from_config(cfg)
    carry = initial_carry_batch(2, cfg.beam_k)
    all_breaks = []
    for c in range(T // W):
        sl = slice(c * W, (c + 1) * W)
        cm, carry = match_batch_carry(
            m._dg, m._du, jnp.asarray(px[:, sl]), jnp.asarray(py[:, sl]),
            jnp.asarray(tm[:, sl]), jnp.asarray(valid[:, sl]), p, cfg.beam_k, carry,
        )
        all_breaks.append(np.asarray(cm.breaks))
    breaks = np.concatenate(all_breaks, axis=1)
    # exactly one break: the start of the trace; none at seams 16/32/48
    assert breaks[:, 0].all()
    assert not breaks[:, 1:].any(), np.argwhere(breaks[:, 1:])


def test_break_exactly_at_seam_boundary(setup):
    """A teleport landing precisely on a chunk seam: the break must be
    flagged at the seam point (the chain program's carried-beam transition,
    not the hoisted precompute, owns that step) and the chunked decode must
    still equal a single-window decode of the same trace."""
    arrays, ubodt = setup
    W = 32

    # W points along the grid's bottom row road, then 2W along the top row:
    # the vehicle teleports the full grid height (~1 km) exactly at point
    # index W — the first seam with length_buckets [16, 32] — while staying
    # on-road on both sides, so only the seam step exceeds breakage
    def _row(y, n, t0):
        xs = np.linspace(float(arrays.node_x.min()) + 5.0,
                         float(arrays.node_x.max()) - 5.0, n)
        lat, lon = arrays.proj.to_latlon(xs, np.full(n, y))
        return [{"lat": float(a), "lon": float(o), "time": t0 + 5.0 * i}
                for i, (a, o) in enumerate(zip(lat, lon))]

    trace = {"uuid": "seam", "trace":
             _row(float(arrays.node_y.min()) + 1.0, W, 1000.0)
             + _row(float(arrays.node_y.max()) - 1.0, 2 * W, 1000.0 + 5.0 * W)}

    cfg_small = MatcherConfig(length_buckets=[16, W], breakage_distance=800.0)
    m_small = SegmentMatcher(arrays=arrays, ubodt=ubodt, config=cfg_small)
    cfg_big = MatcherConfig(length_buckets=[128], breakage_distance=800.0)
    m_big = SegmentMatcher(arrays=arrays, ubodt=ubodt, config=cfg_big)
    chunked = m_small.match(trace)
    whole = m_big.match(trace)
    assert chunked["segments"]
    assert chunked == whole

    # the compact records agree too, and the break sits at column W
    handles = m_small._dispatch_long([trace], [0])
    _grp, (_edge, _off, breaks), _tm = m_small._fetch_long(handles[0])
    assert breaks[0, W], "teleport at the seam was not flagged as a break"
    assert not breaks[0, W + 1 : 2 * W].any()


def test_mixed_short_and_long(setup):
    arrays, ubodt = setup
    cfg = MatcherConfig(length_buckets=[16, 32])
    m = SegmentMatcher(arrays=arrays, ubodt=ubodt, config=cfg)
    traces = _traces(arrays, 2, 80, seed=3) + _traces(arrays, 2, 10, seed=4)
    out = m.match_many(traces)
    assert all(len(r["segments"]) > 0 for r in out)
