import json

import numpy as np
import pytest

from reporter_tpu.matching import SegmentMatcher, MatcherConfig
from reporter_tpu.tiles.network import grid_city
from reporter_tpu.tiles.arrays import build_graph_arrays
from reporter_tpu.tiles.ubodt import build_ubodt


@pytest.fixture(scope="module")
def setup():
    city = grid_city(rows=5, cols=5, spacing_m=150.0)
    arrays = build_graph_arrays(city, cell_size=100.0)
    ubodt = build_ubodt(arrays, delta=2000.0)
    return city, arrays, ubodt


@pytest.fixture(scope="module")
def matcher(setup):
    _, arrays, ubodt = setup
    return SegmentMatcher(arrays=arrays, ubodt=ubodt, config=MatcherConfig())


def make_trace(arrays, pts_xy, t0=1000, dt=15, uuid="veh"):
    lat, lon = arrays.proj.to_latlon(
        np.array([p[0] for p in pts_xy]), np.array([p[1] for p in pts_xy])
    )
    return {
        "uuid": uuid,
        "trace": [
            {"lat": float(a), "lon": float(o), "time": t0 + dt * i, "accuracy": 5}
            for i, (a, o) in enumerate(zip(lat, lon))
        ],
    }


def street_trace(arrays, row_nodes, n, jitter=3.0, seed=1, t0=1000, dt=15):
    rng = np.random.default_rng(seed)
    xs = arrays.node_x[row_nodes]
    ys = arrays.node_y[row_nodes]
    t = np.linspace(0.05, 0.9, n)
    px = np.interp(t, np.linspace(0, 1, len(xs)), xs) + rng.normal(0, jitter, n)
    py = np.interp(t, np.linspace(0, 1, len(ys)), ys) + rng.normal(0, jitter, n)
    return make_trace(arrays, list(zip(px, py)), t0=t0, dt=dt)


class TestMatchWire:
    def test_full_and_partial_segments(self, setup, matcher):
        _, arrays, _ = setup
        trace = street_trace(arrays, [2 * 5 + c for c in range(5)], 10)
        out = json.loads(matcher.Match(json.dumps(trace)))
        segs = out["segments"]
        assert len(segs) >= 3
        # first entered mid-segment, last exited mid-segment
        assert segs[0]["start_time"] == -1 and segs[0]["length"] == -1
        assert segs[-1]["end_time"] == -1 and segs[-1]["length"] == -1
        # middles fully traversed with contiguous times
        for a, b in zip(segs, segs[1:]):
            if a["end_time"] != -1 and b["start_time"] != -1:
                assert a["end_time"] == pytest.approx(b["start_time"], abs=0.01)
        full = [s for s in segs if s["length"] != -1]
        assert full and all(s["length"] == pytest.approx(150.0, rel=0.01) for s in full)
        # schema keys
        for s in segs:
            for key in ("way_ids", "internal", "queue_length", "begin_shape_index", "end_shape_index",
                        "start_time", "end_time", "length"):
                assert key in s

    def test_shape_indices_monotonic(self, setup, matcher):
        _, arrays, _ = setup
        trace = street_trace(arrays, [1 * 5 + c for c in range(5)], 12)
        segs = matcher.match(trace)["segments"]
        idxs = [(s["begin_shape_index"], s["end_shape_index"]) for s in segs]
        for b, e in idxs:
            assert 0 <= b <= e < 12
        for (b1, e1), (b2, e2) in zip(idxs, idxs[1:]):
            assert b2 >= b1 and e2 >= e1

    def test_queue_length_stopped_vehicle(self, setup, matcher):
        _, arrays, _ = setup
        # drive onto the middle street then stop near the end of a block
        row = [2 * 5 + c for c in range(5)]
        y = float(arrays.node_y[row[0]])
        xs = [float(arrays.node_x[row[0]]) + v for v in (10, 60, 110, 140, 141, 142, 143)]
        # crawling at <1 m/s for the last 4 points (15 s apart)
        trace = make_trace(arrays, [(x, y) for x in xs])
        segs = matcher.match(trace)["segments"]
        first = segs[0]
        assert first["queue_length"] > 0

    def test_free_flow_zero_queue(self, setup, matcher):
        _, arrays, _ = setup
        trace = street_trace(arrays, [3 * 5 + c for c in range(5)], 8, dt=5)
        segs = matcher.match(trace)["segments"]
        assert all(s["queue_length"] == 0 for s in segs)


class TestBackendDiff:
    def test_cpu_backend_matches_jax(self, setup):
        _, arrays, ubodt = setup
        jaxm = SegmentMatcher(arrays=arrays, ubodt=ubodt, config=MatcherConfig())
        cpum = SegmentMatcher(arrays=arrays, ubodt=ubodt, config=MatcherConfig(), backend="cpu")
        for seed, row in [(1, 0), (2, 1), (3, 2), (4, 3)]:
            trace = street_trace(arrays, [row * 5 + c for c in range(5)], 10, seed=seed)
            sj = jaxm.match(trace)["segments"]
            sc = cpum.match(trace)["segments"]
            assert [s.get("segment_id") for s in sj] == [s.get("segment_id") for s in sc], seed
            for a, b in zip(sj, sc):
                assert a["start_time"] == pytest.approx(b["start_time"], abs=0.5)
                assert a["end_time"] == pytest.approx(b["end_time"], abs=0.5)


def test_time_factor_cuts_infeasible_speed(setup):
    """A 150 m hop in 1 s (540 km/h) exceeds free-flow time * factor -> the
    matcher should break rather than claim a continuous traversal."""
    _, arrays, ubodt = setup
    m = SegmentMatcher(arrays=arrays, ubodt=ubodt, config=MatcherConfig())
    row = [2 * 5 + c for c in range(5)]
    y = float(arrays.node_y[row[0]])
    xs = [10.0 + float(arrays.node_x[row[0]]), 20.0 + float(arrays.node_x[row[0]]),
          float(arrays.node_x[row[3]]), float(arrays.node_x[row[3]]) + 10.0]
    trace = make_trace(arrays, [(x, y) for x in xs], dt=1)
    segs = m.match(trace)["segments"]
    # discontinuity: some segment boundary must be partial (-1) mid-trace
    boundary_times = [(s["start_time"], s["end_time"]) for s in segs]
    assert any(st == -1 or et == -1 for st, et in boundary_times)


def test_epoch_scale_times_preserved(setup, matcher):
    """Unix-epoch timestamps (~1.7e9 s) must survive the device float32 cast:
    times are rebased per trace before casting, so dt and interpolated
    boundary times keep sub-second precision."""
    _, arrays, _ = setup
    t0 = 1753776000
    trace = street_trace(arrays, [2 * 5 + c for c in range(5)], 10, t0=t0)
    segs = matcher.match(trace)["segments"]
    bounded = [s for s in segs if s["start_time"] != -1]
    assert bounded and all(s["start_time"] >= t0 for s in bounded)
    pairs = [
        (a["end_time"], b["start_time"])
        for a, b in zip(segs, segs[1:])
        if a["end_time"] != -1 and b["start_time"] != -1
    ]
    assert pairs and all(abs(x - y) < 0.01 for x, y in pairs)


def test_mesh_devices_product_path(setup, matcher):
    """cfg.devices=2 routes match_many through dp-sharded jits (the product
    mesh path, VERDICT r03 next #4) and must reproduce the single-device
    results segment-for-segment, including odd batch sizes that need
    dp padding and the long-trace carry path."""
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs the virtual multi-device CPU backend")
    _, arrays, ubodt = setup
    mm = SegmentMatcher(
        arrays=arrays, ubodt=ubodt, config=MatcherConfig(devices=2)
    )
    assert mm._mesh is not None
    row = [2 * 5 + c for c in range(5)]
    traces = [street_trace(arrays, row, 10, seed=s) for s in range(5)]
    # a long trace beyond the largest bucket exercises the sharded carry path
    traces.append(street_trace(arrays, row, 300, seed=99, dt=2))
    got = mm.match_many(traces)
    want = matcher.match_many(traces)
    for g, w in zip(got, want):
        assert g == w


def test_mesh_devices_validation():
    with pytest.raises(ValueError, match="powers of two"):
        city = grid_city(rows=3, cols=3, spacing_m=150.0)
        arrays = build_graph_arrays(city, cell_size=100.0)
        ubodt = build_ubodt(arrays, delta=500.0)
        SegmentMatcher(arrays=arrays, ubodt=ubodt, config=MatcherConfig(devices=3))


def _has_shard_map() -> bool:
    # the parallel.rules shim bridges jax.shard_map (new builds) and
    # jax.experimental.shard_map (0.4.x) — only a build with NEITHER skips
    try:
        from reporter_tpu.parallel.rules import shard_map  # noqa: F401

        return True
    except Exception:  # noqa: BLE001 - capability probe
        return False


@pytest.mark.skipif(not _has_shard_map(),
                    reason="this jax build lacks shard_map entirely")
def test_mesh_graph_sharded_product_path(setup, matcher):
    """devices=8, graph_devices=4: the UBODT lives in 1/4 bucket-range
    slices per chip and the product match_many runs under shard_map with
    collective probe resolution — results must equal single-device
    segment-for-segment (HBM-scaling variant of the mesh path)."""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual CPU backend")
    _, arrays, ubodt = setup
    mm = SegmentMatcher(
        arrays=arrays, ubodt=ubodt,
        config=MatcherConfig(devices=8, graph_devices=4),
    )
    row = [2 * 5 + c for c in range(5)]
    traces = [street_trace(arrays, row, 10, seed=s) for s in range(5)]
    traces.append(street_trace(arrays, row, 300, seed=99, dt=2))
    got = mm.match_many(traces)
    want = matcher.match_many(traces)
    for g, w in zip(got, want):
        assert g == w


def test_mesh_graph_devices_validation(setup):
    _, arrays, ubodt = setup
    with pytest.raises(ValueError, match="divide"):
        SegmentMatcher(arrays=arrays, ubodt=ubodt,
                       config=MatcherConfig(devices=2, graph_devices=4))
