"""The self-driving fleet's control plane (docs/serving-fleet.md
"Self-driving fleet"): the autoscaler's AND-gated decisions, the
router's dynamic replica set + admin surface, adaptive tail control
(hedge threshold + micro-batch fill window), the prober's phase jitter
+ Retry-After honoring, and the new chaos points (clock_skew,
slow_drain)."""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import pytest

from reporter_tpu import faults
from reporter_tpu.matching import MatcherConfig, SegmentMatcher
from reporter_tpu.matching.session import SessionState, SessionStore
from reporter_tpu.obs import adaptive as obs_adaptive
from reporter_tpu.serve.autoscale import Autoscaler, RespawnBackoff
from reporter_tpu.serve.router import FleetRouter
from reporter_tpu.serve.service import (DeadlineExpired, MicroBatcher,
                                        ReporterService)
from reporter_tpu.tiles.arrays import build_graph_arrays
from reporter_tpu.tiles.network import grid_city
from reporter_tpu.tiles.ubodt import build_ubodt

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    for p in faults.POINTS:
        monkeypatch.delenv("REPORTER_FAULT_" + p.upper(), raising=False)
    monkeypatch.delenv("REPORTER_ADAPTIVE", raising=False)
    faults.reset()
    yield
    faults.reset()


@pytest.fixture(scope="module")
def engine():
    city = grid_city(rows=5, cols=5, spacing_m=150.0)
    arrays = build_graph_arrays(city, cell_size=100.0)
    ubodt = build_ubodt(arrays, delta=2000.0)
    return arrays, ubodt


class _Replica:
    """One in-process serve replica with a pinned replica id."""

    def __init__(self, arrays, ubodt, rid, deferred=False, **svc_kw):
        self.rid = rid
        prev = os.environ.get("REPORTER_REPLICA_ID")
        os.environ["REPORTER_REPLICA_ID"] = rid
        try:
            if deferred:
                self.svc = ReporterService(None, **svc_kw)
            else:
                matcher = SegmentMatcher(arrays=arrays, ubodt=ubodt,
                                         config=MatcherConfig(),
                                         backend="cpu")
                self.svc = ReporterService(matcher, max_wait_ms=2.0,
                                           **svc_kw)
        finally:
            if prev is None:
                os.environ.pop("REPORTER_REPLICA_ID", None)
            else:
                os.environ["REPORTER_REPLICA_ID"] = prev
        self.httpd = self.svc.make_server("127.0.0.1", 0)
        self.port = self.httpd.server_port
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()
        self.url = "http://127.0.0.1:%d" % self.port

    def close(self):
        try:
            self.httpd.shutdown()
            self.httpd.server_close()
        except Exception:  # noqa: BLE001
            pass


def post_json(url, payload, timeout=30):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, dict(r.headers), json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), json.loads(e.read().decode())


def get_json(url, timeout=10):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, dict(r.headers), json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), json.loads(e.read().decode())


# -- the adaptive primitives -------------------------------------------------


def test_controller_deadband_step_clamp_cooldown():
    clock = {"t": 0.0}
    c = obs_adaptive.Controller("test_ctl", 0.010, lo=0.002, hi=0.040,
                                deadband=0.10, max_step=0.30,
                                cooldown_s=1.0, clock=lambda: clock["t"])
    # in-deadband targets never move the knob
    assert c.propose(0.0101) == pytest.approx(0.010)
    # an accepted move is step-limited (30% per move)...
    clock["t"] = 2.0
    assert c.propose(0.002) == pytest.approx(0.007)
    # ...and rate-limited: a second move inside the cooldown is ignored
    assert c.propose(0.002) == pytest.approx(0.007)
    clock["t"] = 4.0
    # clamped at the envelope regardless of target
    assert c.propose(0.0001) == pytest.approx(0.0049)
    for i in range(20):
        clock["t"] += 2.0
        c.propose(0.0001)
    assert c.value == pytest.approx(0.002)
    for i in range(40):
        clock["t"] += 2.0
        c.propose(10.0)
    assert c.value == pytest.approx(0.040)
    assert c.revert() == pytest.approx(0.010)


def test_windowed_quantile_rolls_off():
    clock = {"t": 100.0}
    w = obs_adaptive.WindowedQuantile(window_s=10.0,
                                      clock=lambda: clock["t"])
    for _ in range(50):
        w.observe(0.5)
    assert w.count() == 50
    assert w.quantile(0.95) == pytest.approx(0.5, rel=0.25)
    clock["t"] = 120.0  # past the window: the old epoch no longer counts
    assert w.count() == 0
    assert w.quantile(0.95) is None


def test_adaptive_disabled_is_static(monkeypatch):
    monkeypatch.setenv("REPORTER_ADAPTIVE", "0")
    assert not obs_adaptive.enabled()

    class _Stub:
        backend = "cpu"

        def match_many_async(self, traces):
            return lambda: [{"segments": []} for _ in traces]

    b = MicroBatcher(_Stub(), max_wait_ms=10.0, watchdog_s=0)
    assert b._wait_ctl is None
    b._adapt_wait(64)  # no-op, no controller state at all
    assert b.max_wait == pytest.approx(0.010)


def test_batcher_wait_shrinks_when_queue_wait_dominates():
    class _Stub:
        backend = "cpu"

        def match_many_async(self, traces):
            return lambda: [{"segments": []} for _ in traces]

    b = MicroBatcher(_Stub(), max_wait_ms=10.0, watchdog_s=0)
    assert b._wait_ctl is not None
    b._wait_ctl.cooldown_s = 0.0
    # queue wait p95 far above the device step p95: holding the fill
    # window open is the tail — the controller shrinks it
    for _ in range(64):
        b._h_qwait.observe(0.200)
    for _ in range(16):
        b._h_dstep.observe(0.005)
    w0 = b.max_wait
    for _ in range(30):
        b._adapt_wait(fill=1)
    assert b.max_wait < w0
    assert b.max_wait == pytest.approx(b._wait_ctl.lo)
    # device step dominating on full batches: amortisation wins, grow
    b2 = MicroBatcher(_Stub(), max_wait_ms=10.0, watchdog_s=0)
    b2._wait_ctl.cooldown_s = 0.0
    for _ in range(64):
        b2._h_qwait.observe(0.001)
    for _ in range(16):
        b2._h_dstep.observe(0.500)
    for _ in range(40):
        b2._adapt_wait(fill=b2.max_batch)
    assert b2.max_wait > 0.010
    # converges into the deadband around the clamp ceiling
    assert b2.max_wait >= 0.9 * b2._wait_ctl.hi


def test_hedge_threshold_tracks_live_p95(monkeypatch):
    router = FleetRouter(["http://127.0.0.1:1"], hedge_ms=100.0,
                         probe_interval_s=3600.0)
    try:
        assert router._hedge_ctl is not None
        router._hedge_ctl.cooldown_s = 0.0
        # thin traffic: the controller holds (no quantile yanking)
        assert router.current_hedge_s() == pytest.approx(0.1)
        for _ in range(100):
            router.slo.observe("report", 200, 0.400)
        for _ in range(40):
            router.current_hedge_s()
        # k=2 x p95(~0.4s) = 0.8s, inside the [0.01, 1.0] clamp
        assert router.current_hedge_s() == pytest.approx(0.8, rel=0.2)
    finally:
        router.stop()


def test_hedge_threshold_static_without_adaptive(monkeypatch):
    monkeypatch.setenv("REPORTER_ADAPTIVE", "0")
    router = FleetRouter(["http://127.0.0.1:1"], hedge_ms=100.0,
                         probe_interval_s=3600.0)
    try:
        assert router._hedge_ctl is None
        for _ in range(100):
            router.slo.observe("report", 200, 0.400)
        assert router.current_hedge_s() == pytest.approx(0.1)
    finally:
        router.stop()


# -- the autoscaler's decision core ------------------------------------------


def _mk_autoscaler(clock, **kw):
    sig = {"replicas": 2, "queue_depth": 0.0, "burn_alerting": False,
           "max_burn": 0.0}
    actions = {"up": 0, "down": 0}

    def scale_up(reason):
        actions["up"] += 1
        sig["replicas"] += 1
        return True

    def scale_down(reason):
        actions["down"] += 1
        sig["replicas"] -= 1
        return True

    a = Autoscaler(lambda: dict(sig), scale_up, scale_down,
                   min_replicas=1, max_replicas=3, poll_s=1.0,
                   cooldown_s=5.0, queue_high=8.0, window_s=12.0,
                   down_after_s=30.0, clock=lambda: clock["t"])
    return a, sig, actions


def test_burst_alone_cannot_scale_up():
    clock = {"t": 1000.0}
    a, sig, actions = _mk_autoscaler(clock)
    # a 2-second queue burst + burn alert: the fast window fires, the
    # slow window does not — the AND gate holds the fleet steady
    for i in range(120):
        clock["t"] += 1.0
        sig["queue_depth"] = 50.0 if i in (60, 61) else 0.0
        sig["burn_alerting"] = i in (60, 61)
        a.tick()
    # the burst never grew the fleet (the calm stretches legitimately
    # shrink it toward min_replicas — that is the idle path, not a flap)
    assert actions["up"] == 0
    assert sig["replicas"] >= 1


def test_sustained_burn_and_queue_scales_up_once_per_cooldown():
    clock = {"t": 1000.0}
    a, sig, actions = _mk_autoscaler(clock)
    sig["queue_depth"] = 50.0
    sig["burn_alerting"] = True
    sig["max_burn"] = 3.0
    for _ in range(60):
        clock["t"] += 1.0
        a.tick()
    # sustained pressure: scaled up, but never twice inside one cooldown
    assert actions["up"] >= 1
    assert actions["up"] <= 60 / 5.0 + 1
    # and never past max_replicas
    assert sig["replicas"] <= 3


def test_burn_without_queue_pressure_does_not_scale():
    clock = {"t": 1000.0}
    a, sig, actions = _mk_autoscaler(clock)
    sig["burn_alerting"] = True   # latency pain, empty queues: a traffic
    sig["max_burn"] = 5.0         # mix problem a replica cannot fix
    for _ in range(60):
        clock["t"] += 1.0
        a.tick()
    assert actions["up"] == 0


def test_sustained_calm_scales_down_to_min():
    clock = {"t": 1000.0}
    a, sig, actions = _mk_autoscaler(clock)
    sig["replicas"] = 3
    for _ in range(120):
        clock["t"] += 1.0
        a.tick()
    assert actions["down"] >= 1
    assert sig["replicas"] == 1  # and never below min_replicas
    n_down = actions["down"]
    for _ in range(60):
        clock["t"] += 1.0
        a.tick()
    assert actions["down"] == n_down


def test_unreachable_router_makes_no_decisions():
    clock = {"t": 1000.0}
    calls = {"n": 0}

    def boom(reason):
        calls["n"] += 1
        return True

    a = Autoscaler(lambda: None, boom, boom, clock=lambda: clock["t"],
                   cooldown_s=0.0)
    for _ in range(50):
        clock["t"] += 1.0
        assert a.tick() is None
    assert calls["n"] == 0


def test_respawn_backoff_doubles_and_resets():
    backoff = RespawnBackoff(base_s=0.5, max_s=8.0, healthy_reset_s=30.0)
    # a one-off death respawns immediately (today's fast recovery)
    assert backoff.next_delay("rep-0", uptime_s=2.0) == 0.0
    d1 = backoff.next_delay("rep-0", uptime_s=0.5)
    d2 = backoff.next_delay("rep-0", uptime_s=0.5)
    d3 = backoff.next_delay("rep-0", uptime_s=0.5)
    assert 0.5 <= d1 <= 1.0          # base x [1, 2) full jitter
    assert 1.0 <= d2 <= 2.0
    assert 2.0 <= d3 <= 4.0
    # a long healthy life resets the streak
    assert backoff.next_delay("rep-0", uptime_s=120.0) == 0.0
    # independent per child
    assert backoff.next_delay("rep-1", uptime_s=0.1) == 0.0


# -- the router's dynamic replica set ----------------------------------------


def test_router_admin_add_remove_and_scale_events(engine):
    arrays, ubodt = engine
    reps = [_Replica(arrays, ubodt, "rep-%d" % i) for i in range(2)]
    extra = _Replica(arrays, ubodt, "rep-2")
    router = FleetRouter([r.url for r in reps], probe_interval_s=0.2)
    router.start()
    httpd = router.make_server("127.0.0.1", 0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    url = "http://127.0.0.1:%d" % httpd.server_port
    try:
        st, _h, body = post_json(url + "/fleet",
                                 {"add": extra.url,
                                  "reason": "burn_and_queue"})
        assert st == 200 and body["ok"]
        assert len(router.replicas) == 3
        # idempotent: adding the same url again conflicts, no dup
        st, _h, body = post_json(url + "/fleet", {"add": extra.url})
        assert st == 409 and len(router.replicas) == 3
        # the event ring + counter surface on /statusz
        st, _h, sz = get_json(url + "/statusz")
        assert st == 200
        events = sz["autoscale"]["events"]
        assert any(e["direction"] == "up"
                   and e["reason"] == "burn_and_queue" for e in events)
        fam = sz["metrics"]["reporter_fleet_scale_events_total"]
        assert any(lv == ["up", "burn_and_queue"] and v >= 1
                   for lv, v in fam["samples"])
        # the added replica becomes routable (probe marks it healthy)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if any(r.url == extra.url and r.available()
                   for r in router.replicas):
                break
            time.sleep(0.1)
        else:
            pytest.fail("added replica never became available")
        # remove by replica id
        st, _h, body = post_json(url + "/fleet",
                                 {"remove": "rep-2", "reason": "idle"})
        assert st == 200 and body["ok"]
        assert len(router.replicas) == 2
        # the last replica can never be removed
        post_json(url + "/fleet", {"remove": reps[0].rid})
        st, _h, body = post_json(url + "/fleet", {"remove": reps[1].rid})
        assert st == 409 and "last replica" in body["admin"]
        # malformed admin bodies are 400
        st, _h, body = post_json(url + "/fleet", {"nope": 1})
        assert st == 400
    finally:
        httpd.shutdown()
        httpd.server_close()
        router.stop()
        for r in reps + [extra]:
            r.close()


def test_added_replica_warming_holdout_serves_nothing_cold(engine):
    arrays, ubodt = engine
    warm = _Replica(arrays, ubodt, "rep-warm")
    cold = _Replica(arrays, ubodt, "rep-cold", deferred=True)
    router = FleetRouter([warm.url], probe_interval_s=0.1)
    router.start()
    try:
        ok, _msg = router.add_replica(cold.url, "burn_and_queue")
        assert ok
        time.sleep(0.5)
        cold_rep = next(r for r in router.replicas if r.url == cold.url)
        # the warming hold-out: in the ring, NOT routable
        assert cold_rep.state == "init"
        assert not cold_rep.available()
        for k in range(8):
            order, _ = router.route_order("veh-%d" % k)
            assert all(r.url != cold.url for r in order)
        # engine attaches -> the probe admits it (and, was_lost being
        # set, the first healthy transition counts as a recovery so the
        # session rebalance will pull its vehicles' beams over)
        assert cold_rep.was_lost
        matcher = SegmentMatcher(arrays=arrays, ubodt=ubodt,
                                 config=MatcherConfig(), backend="cpu")
        cold.svc.attach_matcher(matcher)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and not cold_rep.available():
            time.sleep(0.1)
        assert cold_rep.available()
    finally:
        router.stop()
        warm.close()
        cold.close()


def test_router_rehomes_checkpointed_sessions(engine):
    arrays, ubodt = engine
    reps = [_Replica(arrays, ubodt, "rep-%d" % i) for i in range(2)]
    router = FleetRouter([r.url for r in reps], probe_interval_s=0.2)
    router.start()
    httpd = router.make_server("127.0.0.1", 0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    url = "http://127.0.0.1:%d" % httpd.server_port
    try:
        time.sleep(0.3)  # first probes
        wires = []
        for k in range(6):
            s = SessionState("veh-re-%d" % k, t0=1000.0)
            s.points_total = 3
            s.replay = [{"lat": 37.75, "lon": -122.45, "time": 1000 + i}
                        for i in range(3)]
            s.seq = 1
            wires.append(s.to_wire())
        st, _h, body = post_json(url + "/sessions", {"sessions": wires})
        assert st == 200
        assert body["rehomed"] == 6 and body["no_target"] == 0
        assert sorted(body["imported_uuids"]) == sorted(
            w["uuid"] for w in wires)
        # every session landed on its uuid's rendezvous primary
        for w in wires:
            order, _ = router.route_order(w["uuid"])
            primary = next(r for r in reps
                           if r.url == order[0].url)
            assert primary.svc.session_store.peek(w["uuid"]) is not None
        # ...and the ledger carried over exactly
        total = sum(
            r.svc.session_store.summary()["points_total"] for r in reps)
        assert total == 18
        # "exclude" reroutes around a replica the caller knows is dead
        # (the supervisor's re-home fires before the probe streak does)
        s = SessionState("veh-excl", t0=1000.0)
        s.points_total = 1
        s.replay = [{"lat": 37.75, "lon": -122.45, "time": 2000}]
        order, _ = router.route_order("veh-excl")
        primary_rid = next(r.rid for r in reps if r.url == order[0].url)
        other = next(r for r in reps if r.rid != primary_rid)
        st, _h, body = post_json(url + "/sessions",
                                 {"sessions": [s.to_wire()],
                                  "exclude": primary_rid})
        assert st == 200 and body["rehomed"] == 1
        assert other.svc.session_store.peek("veh-excl") is not None
    finally:
        httpd.shutdown()
        httpd.server_close()
        router.stop()
        for r in reps:
            r.close()


# -- prober: phase jitter + Retry-After --------------------------------------


def test_probe_schedule_jitter_spreads_phases():
    router = FleetRouter(["http://127.0.0.1:1"], probe_interval_s=1.0)
    try:
        r = router.replicas[0]
        delays = []
        for _ in range(200):
            router._schedule_probe(r)
            delays.append(r.next_probe_at - time.monotonic())
        assert min(delays) >= 0.99
        assert max(delays) <= 1.0 + router.probe_jitter + 0.01
        assert max(delays) - min(delays) > 0.05  # actually jittered
    finally:
        router.stop()


def test_draining_probe_honors_retry_after_no_streak(engine):
    arrays, ubodt = engine
    rep = _Replica(arrays, ubodt, "rep-drn")
    router = FleetRouter([rep.url], probe_interval_s=0.2,
                         unhealthy_after=2)
    try:
        router.probe_all()
        r = router.replicas[0]
        assert r.state == "healthy"
        rep.svc.begin_drain()
        t0 = time.monotonic()
        router.probe_all()
        assert r.state == "draining"
        # 503-draining never counts toward the unhealthy streak...
        assert r.probe_fail_streak == 0
        assert r.state != "unhealthy"
        # ...and its Retry-After (1 s on the drain responses) pushes the
        # NEXT probe of this replica back past the normal 0.2 s interval
        assert r.next_probe_at - t0 >= 0.9
    finally:
        router.stop()
        rep.close()


# -- new chaos points --------------------------------------------------------


def test_clock_skew_expires_queued_deadlines(monkeypatch):
    class _Stub:
        backend = "cpu"

        def match_many_async(self, traces):
            return lambda: [{"segments": []} for _ in traces]

    b = MicroBatcher(_Stub(), max_wait_ms=50.0, watchdog_s=0)
    # untouched: a generous deadline survives the queue
    f = b.submit({"uuid": "v"}, deadline=time.monotonic() + 5.0)
    assert f.result(timeout=10) == {"segments": []}
    # armed at 1000x (decimal form — a bare integer is the raise-N
    # grammar): the ~50 ms batch-fill wait scales to ~50 s of effective
    # queue time and the same deadline expires pre-dispatch
    monkeypatch.setenv("REPORTER_FAULT_CLOCK_SKEW", "1000.0")
    faults.reset()
    f = b.submit({"uuid": "v"}, deadline=time.monotonic() + 5.0)
    with pytest.raises(DeadlineExpired):
        f.result(timeout=10)


def test_slow_drain_stalls_session_export(monkeypatch, engine):
    arrays, ubodt = engine
    rep = _Replica(arrays, ubodt, "rep-slow")
    try:
        monkeypatch.setenv("REPORTER_FAULT_SLOW_DRAIN", "0.4:1")
        faults.reset()
        t0 = time.monotonic()
        code, body = rep.svc.handle_sessions({"export": ["1"]})
        dt_armed = time.monotonic() - t0
        assert code == 200 and "sessions" in body
        assert dt_armed >= 0.4
        # the count-limited spec disarms after one firing: judge the
        # disarmed export against the armed one (monotonic deltas), not
        # an absolute wall ceiling a loaded single-CPU host can blow
        t0 = time.monotonic()
        rep.svc.handle_sessions({"export": ["1"]})
        assert time.monotonic() - t0 < dt_armed - 0.2
    finally:
        rep.close()


# -- adaptive max_batch (the third knob, ISSUE 14) --------------------------


class _BatchStub:
    backend = "cpu"

    def match_many_async(self, traces):
        return lambda: [{"segments": []} for _ in traces]


def test_batch_width_shrinks_when_device_step_dominates():
    """Full batches whose device-step p95 dwarfs the queue tail mean the
    batch width IS the latency: the controller narrows it, clamped to
    static/4, and glides back to the static cap once the step calms."""
    b = MicroBatcher(_BatchStub(), max_batch=64, max_wait_ms=10.0,
                     watchdog_s=0)
    assert b._batch_ctl is not None
    b._batch_ctl.cooldown_s = 0.0
    b._wait_ctl.cooldown_s = 0.0
    for _ in range(64):
        b._h_qwait.observe(0.002)
    for _ in range(16):
        b._h_dstep.observe(0.500)
    for _ in range(40):
        b._adapt_wait(fill=b.max_batch)
    assert b.max_batch < 64
    assert b.max_batch == max(1, int(round(b._batch_ctl.lo)))
    # never widens past the operator's static cap
    assert b._batch_ctl.hi == 64.0
    # calm step: glide back toward static
    b._h_qwait = obs_adaptive.WindowedQuantile(window_s=30.0)
    b._h_dstep = obs_adaptive.WindowedQuantile(window_s=60.0)
    for _ in range(64):
        b._h_qwait.observe(0.010)
    for _ in range(16):
        b._h_dstep.observe(0.012)
    for _ in range(40):
        b._adapt_wait(fill=1)
    assert b.max_batch >= 0.9 * 64


def test_batch_width_static_without_fill_pressure():
    """A dominating step on batches that do NOT fill is a fill-window
    story, not a width story — the width knob must not move."""
    b = MicroBatcher(_BatchStub(), max_batch=64, max_wait_ms=10.0,
                     watchdog_s=0)
    b._batch_ctl.cooldown_s = 0.0
    for _ in range(64):
        b._h_qwait.observe(0.002)
    for _ in range(16):
        b._h_dstep.observe(0.500)
    for _ in range(20):
        b._adapt_wait(fill=3)
    assert b.max_batch == 64


def test_batch_width_static_with_adaptive_off(monkeypatch):
    monkeypatch.setenv("REPORTER_ADAPTIVE", "0")
    b = MicroBatcher(_BatchStub(), max_batch=64, max_wait_ms=10.0,
                     watchdog_s=0)
    assert b._batch_ctl is None
    for _ in range(20):
        b._adapt_wait(fill=64)  # no controller state at all
    assert b.max_batch == 64
