"""bench.py orchestrator acquisition schedule, unit-tested in-process.

The schedule is the round artifact's critical path (round 4 lost its TPU
number to a 180 s give-up against an 8 h relay outage).  These tests mock
the process-spawning seams and script the relay-port sequence to pin the
decision logic: bank-once CPU fallback, attempt-on-listen, TPU result
wins, budget expiry settles for the bank.
"""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import bench


class FakeProc:
    def __init__(self, rc=0):
        self.returncode = rc
        self.stdout = None

    def poll(self):
        return self.returncode

    def wait(self):
        return self.returncode

    def kill(self):
        pass


class FakeGate:
    """BaselineGate stand-in: always has a baseline result."""

    def __init__(self, *a, **kw):
        self.rc = 0
        self.json = {"cpu_traces_per_sec": 10.0, "cpu_points_per_sec": 2000.0,
                     "baseline_secs": 60.0}

    def poll(self):
        pass

    def ensure(self, timeout):
        pass


TPU_JSON = {"platform": "tpu", "value": 500.0, "points_per_sec": 100000.0,
            "kernel_points_per_sec": 120000.0}
CPU_JSON = {"platform": "cpu", "value": 50.0, "points_per_sec": 10000.0,
            "kernel_points_per_sec": 11000.0}


@pytest.fixture()
def rig(monkeypatch, tmp_path, capsys):
    """Patch every process/port seam; returns a dict the test scripts."""
    state = {"ports_seq": [], "attempt_results": [], "cpu_runs": 0,
             "attempts_made": 0, "now": [0.0]}

    monkeypatch.chdir(tmp_path)  # BENCH_PARTIAL.json lands here

    state["ports_last"] = []

    def fake_ports():
        # pop the scripted sequence; once exhausted, repeat the last value
        # (main() polls once for diagnostics before the schedule loop)
        if state["ports_seq"]:
            state["ports_last"] = state["ports_seq"].pop(0)
        return state["ports_last"]

    def fake_spawn(role, env, status_file=None):
        return FakeProc()

    def fake_monitor(proc, sf, wait, grace, attempts, gate=None):
        return True

    def fake_finish_device(proc, timeout, sf):
        state["attempts_made"] += 1
        if state["attempt_results"]:
            return 0, state["attempt_results"].pop(0)
        return 3, None

    def fake_finish(proc, timeout):
        # only the CPU-fallback worker goes through _finish in the loop
        state["cpu_runs"] += 1
        return 0, dict(CPU_JSON)

    # virtual clock: every sleep/poll advances it so the deadline loop
    # terminates fast.  Installed as a module PROXY in bench's namespace
    # only — patching the real time module's functions would hand the
    # virtual clock to every daemon thread the preceding test files leave
    # running (samplers, batcher finishers, router probers), whose polls
    # then burn the 300 s schedule budget before the scripted relay port
    # ever opens (the full-suite-only flake this replaced).
    class VirtualTime:
        def time(self):
            state["now"][0] += 1.0
            return state["now"][0]

        def sleep(self, s):
            state["now"][0] += s

        def __getattr__(self, name):  # strftime etc. stay real
            import time as _time
            return getattr(_time, name)

    monkeypatch.setattr(bench, "_relay_ports_open", fake_ports)
    monkeypatch.setattr(bench, "_spawn", fake_spawn)
    monkeypatch.setattr(bench, "_monitor_device", fake_monitor)
    monkeypatch.setattr(bench, "_finish_device", fake_finish_device)
    monkeypatch.setattr(bench, "_finish", fake_finish)
    monkeypatch.setattr(bench, "BaselineGate", FakeGate)
    monkeypatch.setattr(bench, "time", VirtualTime())
    monkeypatch.setenv("BENCH_TPU_WAIT", "300")
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    monkeypatch.delenv("BENCH_ROLE", raising=False)
    state["capsys"] = capsys
    return state


def _run(rig):
    rc = bench.main()
    out = rig["capsys"].readouterr().out.strip().splitlines()
    return rc, json.loads(out[-1])


def test_tpu_on_first_listen(rig):
    rig["ports_seq"] = [[8083]]
    rig["attempt_results"] = [dict(TPU_JSON)]
    rc, out = _run(rig)
    assert rc == 0
    assert out["platform"] == "tpu"
    assert out["value"] == 500.0
    assert out["vs_baseline"] == 50.0  # 100000 / 2000
    assert rig["cpu_runs"] == 0  # ports open: never banked a fallback


def test_relay_down_banks_once_then_tpu(rig):
    # several closed polls, then the relay appears and the attempt lands
    rig["ports_seq"] = [[], [], [], [8083]]
    rig["attempt_results"] = [dict(TPU_JSON)]
    rc, out = _run(rig)
    assert rc == 0
    assert out["platform"] == "tpu"
    assert rig["cpu_runs"] == 1  # banked exactly once while waiting
    # the bank is removed once the real artifact prints
    assert not os.path.exists("BENCH_PARTIAL.json")


def test_budget_expiry_settles_for_bank(rig):
    rig["ports_seq"] = []  # relay never comes back
    rc, out = _run(rig)
    assert rc == 0
    assert out["platform"] == "cpu"
    assert out["value"] == 50.0
    assert rig["cpu_runs"] == 1  # no tight respawn loop


def test_failed_attempts_keep_retrying_until_budget(rig):
    rig["ports_seq"] = [[8083]] * 100  # relay up, attempts keep dying
    rig["attempt_results"] = []  # every attempt returns None
    rc, out = _run(rig)
    assert rc == 0
    assert out["platform"] == "cpu"  # final fallback ran
    assert rig["attempts_made"] >= 2  # it retried, not gave up after one


def test_axon_yielding_cpu_is_kept_as_bank(rig):
    # attempt completes but on cpu devices; budget then expires
    rig["ports_seq"] = [[8083]]
    rig["attempt_results"] = [dict(CPU_JSON)]
    rc, out = _run(rig)
    assert rc == 0
    assert out["platform"] == "cpu"
    assert rig["cpu_runs"] == 0  # the axon-cpu result IS the bank


def test_finish_device_kills_ports_open_wedge(monkeypatch, tmp_path):
    """A worker whose status file freezes mid-bench with relay ports OPEN
    (the 2026-07-31 tunnel compile-helper wedge: 'benching' status, both
    ports listening, zero progress for 10+ min) must be killed after
    STATUS_FROZEN_KILL_S instead of running out the full run budget."""
    sf = tmp_path / "status.json"
    sf.write_text(json.dumps({"phase": "benching", "platform": "tpu", "t": 1.0}))

    killed = []

    class WedgedProc:
        class _Out:
            @staticmethod
            def read():
                return b""

        stdout = _Out()
        returncode = None

        def poll(self):
            return 1 if killed else None

        def kill(self):
            killed.append(True)
            self.returncode = 1

        def wait(self):
            return self.returncode

    now = [0.0]
    monkeypatch.setattr(bench.time, "time", lambda: now[0])
    monkeypatch.setattr(bench.time, "sleep",
                        lambda s: now.__setitem__(0, now[0] + s))
    monkeypatch.setattr(bench, "_relay_ports_open", lambda: [8083, 8082])

    run_budget = 2400.0
    rc, dj = bench._finish_device(WedgedProc(), run_budget, str(sf))
    assert killed, "wedged worker was not killed"
    assert dj is None
    # killed by the frozen-status watchdog, well before the run budget
    assert bench.STATUS_FROZEN_KILL_S <= now[0] < run_budget - 60


def test_last_onchip_provenance():
    """Every emitted bench line carries the newest verified on-chip
    capture's provenance (VERDICT r05 next #1c): tpu-platform captures
    only, newest date, best same-day headline, with the fields the doc
    schema names."""
    lo = bench._last_onchip()
    assert lo is not None, "repo ships on-chip captures; provenance missing"
    assert lo["file"].startswith("docs/measurements/")
    assert lo["traces_per_sec"] and lo["captured"]
    # the 2026-07-31 headline capture (3116 tr/s, device_util 1.0) must win
    # over the same-day kernel-compare capture (2321 tr/s)
    assert lo["traces_per_sec"] > 3000
    # cpu-platform measurement files must never masquerade as chip evidence
    import glob
    import json as _json
    import os as _os

    repo = _os.path.dirname(_os.path.abspath(bench.__file__))
    src = _json.load(open(_os.path.join(repo, lo["file"])))
    assert src.get("platform") == "tpu"
