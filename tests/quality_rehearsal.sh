#!/usr/bin/env bash
# Match-quality gating rehearsal (the CI `quality-rehearsal` leg;
# runnable locally — docs/match-quality.md):
#
#   1. no-fault: a warmed serve with shadow-oracle sampling at 1-in-1
#      replays the pinned synth corpus — a dense fleet plus the
#      `--gap-s 45,60` sparse fleet (the reference BatchingProcessor
#      operating point, ROADMAP open item 4).  The server's /debug/slo
#      quality snapshot must PASS tools/quality_gate.py against the
#      committed QUALITY_BASELINE.json, the agreement objective must be
#      ok and not alerting, and loadgen's --server-slo verdict must
#      agree.
#
#   2. injected quality_skew (faults.py): the SAME load against a server
#      whose device batches are silently perturbed.  The serving plane
#      stays green (that is the point — availability and latency cannot
#      see a quality drift), but the shadow oracle does: the agreement
#      objective must be VIOLATING + alerting and the SAME quality gate
#      must FAIL.
#
#   3. uncalibrated-params control (the sparse-model leg's counterweight,
#      docs/match-quality.md "Sparse gaps"): the SAME load against a
#      server with REPORTER_SPARSE=0 — the pre-sparse dense model.  The
#      committed baseline encodes the CALIBRATED sparse accuracy on the
#      45/60/90 s cohorts, so this leg's gate run must FAIL: if it ever
#      passes, the baseline has stopped enforcing the recovered accuracy
#      and regenerating it was dishonest.
#
# Leg 1's corpus includes the sparse fleets (--gap-s 45,60 and
# --gap-s 45,60,90 with --gap-jitter) served by the CALIBRATED sparse
# model (REPORTER_SPARSE defaults on in serve; REPORTER_CALIBRATION
# points at the committed CALIBRATION.json).
#
# Baseline refresh: QUALITY_BASELINE_OUT=<path> writes leg 1's snapshot
# instead of judging it (commit the result as QUALITY_BASELINE.json).
# Regenerate CALIBRATION.json first (tools/calibrate.py) so the baseline
# records calibrated accuracy — never hand-edit either file.
#
# Usage: tests/quality_rehearsal.sh [workdir]
set -euo pipefail

cd "$(dirname "$0")/.."
export PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}"
export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
# the pinned per-cohort sparse calibration (tools/calibrate.py); serve
# boots with the sparse model on by default and loads this table
export REPORTER_CALIBRATION="${REPORTER_CALIBRATION:-$PWD/CALIBRATION.json}"

WORK="${1:-$(mktemp -d /tmp/reporter-quality.XXXXXX)}"
mkdir -p "$WORK"
PORT=18071
PORT2=18072
PORT3=18073
echo "quality rehearsal workdir: $WORK"

PIDS=()
cleanup() {
    for pid in "${PIDS[@]}"; do
        kill "$pid" 2>/dev/null || true
    done
    for pid in "${PIDS[@]}"; do
        for _ in $(seq 1 20); do
            kill -0 "$pid" 2>/dev/null || break
            sleep 0.5
        done
        kill -9 "$pid" 2>/dev/null || true
        wait "$pid" 2>/dev/null || true
    done
}
trap cleanup EXIT

# grid matches loadgen's synth default (8x8 @ 200 m); one 16-pt length
# bucket keeps --warmup fast; shadow sampling 1-in-1 so every request is
# scored; the quality worker unthrottled (this is a rehearsal box, not a
# production replica — fidelity here is the verdict, not the p99)
cat > "$WORK/config.json" <<EOF
{
  "network": {"type": "grid", "rows": 8, "cols": 8, "spacing_m": 200},
  "matcher": {"sigma_z": 4.07, "beta": 3.0, "search_radius": 50.0,
              "length_buckets": [16]},
  "backend": "jax",
  "batch": {"max_batch": 64, "max_wait_ms": 5},
  "slo": {"window_s": 120, "availability": 0.95,
          "latency": {"*": {"p99_ms": 8000}}},
  "quality": {"sample_every": 1, "queue_max": 256, "window_s": 600,
              "target": 0.90}
}
EOF
export REPORTER_QUALITY_PACE=0

# the pinned corpus: a dense fleet + the sparse 45/60 s fleet, fixed
# seeds — the SAME arguments produced QUALITY_BASELINE.json
DENSE_ARGS=(--rate 12 --duration 5 --vehicles 10 --points 32 --window 16
            --grid 8 --seed 7 --concurrency 16 --timeout-s 8
            --slo-availability 0.95 --slo-p99-ms 8000)
SPARSE_ARGS=(--rate 12 --duration 5 --vehicles 10 --points 32 --window 16
             --grid 8 --seed 11 --gap-s 45,60 --concurrency 16 --timeout-s 8
             --slo-availability 0.95 --slo-p99-ms 8000)
# the calibrated-sparse-model leg's corpus: the full sparse operating
# band incl. 90 s windows, with per-point gap jitter so the cohort
# boundaries are exercised by non-metronomic gaps (the artifact records
# the realized histogram)
SPARSE90_ARGS=(--rate 12 --duration 5 --vehicles 12 --points 32 --window 16
               --grid 8 --seed 13 --gap-s 45,60,90 --gap-jitter 0.2
               --concurrency 16 --timeout-s 8
               --slo-availability 0.95 --slo-p99-ms 8000)

wait_up() {
    local port=$1 tries=$2
    for _ in $(seq 1 "$tries"); do
        python - <<EOF && return 0 || sleep 1
import json, sys, urllib.request
try:
    h = json.load(urllib.request.urlopen(
        "http://127.0.0.1:$port/health", timeout=2))
except Exception:
    sys.exit(1)
sys.exit(0 if h.get("status") == "ok" and h.get("backend") else 1)
EOF
    done
    return 1
}

drain_quality() {
    # wait for the shadow-oracle queue to empty so the snapshot covers
    # every sampled request
    local port=$1
    python - <<EOF
import json, sys, time, urllib.request
deadline = time.time() + 120
last = -1
while time.time() < deadline:
    slo = json.load(urllib.request.urlopen(
        "http://127.0.0.1:$port/debug/slo", timeout=5))
    q = slo.get("quality") or {}
    if q.get("queue_depth", 1) == 0 and q.get("samples_compared", 0) == last:
        json.dump(slo, open("$WORK/slo_snapshot.json", "w"))
        print("quality drained: %d compared, %d dropped"
              % (q.get("samples_compared", 0), q.get("samples_dropped", 0)))
        sys.exit(0)
    last = q.get("samples_compared", 0)
    time.sleep(1.0)
sys.exit("quality queue never drained")
EOF
}

run_legs() {
    local port=$1 tag=$2
    python tools/loadgen.py --url "http://127.0.0.1:$port" \
        "${DENSE_ARGS[@]}" --server-slo \
        --out "$WORK/loadgen_dense_$tag.json"
    python tools/loadgen.py --url "http://127.0.0.1:$port" \
        "${SPARSE_ARGS[@]}" --server-slo \
        --out "$WORK/loadgen_sparse_$tag.json"
    python tools/loadgen.py --url "http://127.0.0.1:$port" \
        "${SPARSE90_ARGS[@]}" --server-slo \
        --out "$WORK/loadgen_sparse90_$tag.json"
}

# ---- leg 1: no fault — the gate must pass --------------------------------
echo "== leg 1: no-fault (warmed serve + shadow sampling, gate must pass) =="
python -m reporter_tpu.serve --warmup "$WORK/config.json" "127.0.0.1:$PORT" \
    > "$WORK/serve_nofault.log" 2>&1 &
SERVE_PID=$!
PIDS+=("$SERVE_PID")
if ! wait_up "$PORT" 240; then
    echo "FAIL: no-fault service never came up; tail of serve log:"
    tail -20 "$WORK/serve_nofault.log"
    exit 1
fi

run_legs "$PORT" nofault
drain_quality "$PORT"
mv "$WORK/slo_snapshot.json" "$WORK/slo_nofault.json"

if [ -n "${QUALITY_BASELINE_OUT:-}" ]; then
    python - <<EOF
import json
slo = json.load(open("$WORK/slo_nofault.json"))
json.dump(slo["quality"], open("$QUALITY_BASELINE_OUT", "w"), indent=1)
print("baseline written to $QUALITY_BASELINE_OUT — commit it as "
      "QUALITY_BASELINE.json")
EOF
    exit 0
fi

python tools/quality_gate.py QUALITY_BASELINE.json \
    --fresh "$WORK/slo_nofault.json" --min-agreement 0.85 \
    > "$WORK/quality_gate_nofault.json"
echo "no-fault leg: quality gate PASSED"

python - <<EOF
# the agreement objective is live, ok, and not alerting; the sparse
# 45-60 s cohort actually got sampled (the whole point of --gap-s)
import json
slo = json.load(open("$WORK/slo_nofault.json"))
agr = [o for o in slo["objectives"] if o["kind"] == "agreement"]
assert agr and agr[0]["ok"] and not agr[0]["alerting"], agr
assert agr[0]["value"] is not None
cohorts = slo["quality"]["cohorts"]
sparse = [k for k in cohorts if "gap=45-60" in k or "gap=ge60" in k]
assert sparse, "no sparse-gap cohort sampled: %s" % list(cohorts)
for lg in ("loadgen_dense_nofault", "loadgen_sparse_nofault",
           "loadgen_sparse90_nofault"):
    art = json.load(open("$WORK/%s.json" % lg))
    assert art["slo"]["agree"] is True, lg
    assert art["slo"]["server_quality"] is not None, lg
# the jittered sparse corpus proves its spread: the artifact's realized
# gap histogram must be non-degenerate and sparse-dominated
art = json.load(open("$WORK/loadgen_sparse90_nofault.json"))
h = art["gap_histogram"]
assert h and h["count"] > 0, h
assert h["max_s"] > h["min_s"], "gap jitter produced uniform gaps: %s" % h
sparse_pts = h["buckets"]["45-60"] + h["buckets"]["ge60"] + h["buckets"]["30-45"]
assert sparse_pts > h["count"] // 2, h
print("agreement %.4f ok; sparse cohorts sampled: %s"
      % (agr[0]["value"], sparse))
print("sparse90 realized gaps: %s" % h)
EOF

python - <<EOF
# the sparse model itself is live and CALIBRATED on the serving path
# (statusz sparse block + the reporter_sparse_calibrated gauge)
import json, urllib.request
st = json.load(urllib.request.urlopen(
    "http://127.0.0.1:$PORT/statusz", timeout=5))
sp = st.get("sparse") or {}
assert sp.get("enabled") is True, sp
assert sp.get("calibrated") is True, (
    "sparse model running UNCALIBRATED params — is REPORTER_CALIBRATION "
    "pointing at CALIBRATION.json? %s" % sp)
print("sparse model: enabled + calibrated (%s)" % sp.get("calibration"))
EOF

kill "$SERVE_PID" 2>/dev/null || true
wait "$SERVE_PID" 2>/dev/null || true

# ---- leg 2: quality_skew — serving green, quality gate must fail ---------
echo "== leg 2: injected quality_skew (silent drift, gate must FAIL) =="
REPORTER_FAULT_QUALITY_SKEW="60.0" \
python -m reporter_tpu.serve --warmup "$WORK/config.json" "127.0.0.1:$PORT2" \
    > "$WORK/serve_skew.log" 2>&1 &
SERVE_PID=$!
PIDS+=("$SERVE_PID")
if ! wait_up "$PORT2" 240; then
    echo "FAIL: skew-leg service never came up; tail of serve log:"
    tail -20 "$WORK/serve_skew.log"
    exit 1
fi

# no --server-slo here, deliberately: the serving objectives stay green
# under the skew (latency/availability cannot see it) and the server's
# agreement objective is EXPECTED to violate — what must catch it is the
# quality gate below, not the load generator
python tools/loadgen.py --url "http://127.0.0.1:$PORT2" \
    "${DENSE_ARGS[@]}" --out "$WORK/loadgen_dense_skew.json"
python tools/loadgen.py --url "http://127.0.0.1:$PORT2" \
    "${SPARSE_ARGS[@]}" --out "$WORK/loadgen_sparse_skew.json"
drain_quality "$PORT2"
mv "$WORK/slo_snapshot.json" "$WORK/slo_skew.json"

set +e
python tools/quality_gate.py QUALITY_BASELINE.json \
    --fresh "$WORK/slo_skew.json" --min-agreement 0.85 \
    > "$WORK/quality_gate_skew.json"
SKEW_RC=$?
set -e
if [ "$SKEW_RC" -ne 1 ]; then
    echo "FAIL: quality gate rc $SKEW_RC under injected skew (want 1)"
    cat "$WORK/quality_gate_skew.json"
    exit 1
fi

python - <<EOF
# the drift is visible exactly where it should be: serving SLO green,
# agreement objective violating + alerting
import json
slo = json.load(open("$WORK/slo_skew.json"))
agr = [o for o in slo["objectives"] if o["kind"] == "agreement"][0]
assert agr["value"] is not None and not agr["ok"], agr
assert agr["alerting"], agr
serving = [o for o in slo["objectives"] if o["kind"] != "agreement"]
assert all(o["ok"] for o in serving), serving
dense = json.load(open("$WORK/loadgen_dense_skew.json"))
assert dense["slo"]["client"]["ok"] is True  # the drift IS silent on the wire
print("skew leg: serving green, agreement %.4f violating+alerting, "
      "gate rc 1 — the quality plane catches what the serving plane "
      "cannot" % agr["value"])
EOF

kill "$SERVE_PID" 2>/dev/null || true
wait "$SERVE_PID" 2>/dev/null || true

# ---- leg 3: uncalibrated-params control — the gate must FAIL -------------
# REPORTER_SPARSE=0 serves the pre-sparse dense model over the same
# corpora.  The committed baseline records the CALIBRATED sparse accuracy
# at the 45/60/90 s cohorts, so judging the dense model against it must
# regress: this is the leg that proves the regenerated baseline actually
# enforces the recovered accuracy (a baseline lenient enough to bless the
# old model would pass here — and fail the rehearsal).
echo "== leg 3: REPORTER_SPARSE=0 control (uncalibrated params, gate must FAIL) =="
REPORTER_SPARSE=0 \
python -m reporter_tpu.serve --warmup "$WORK/config.json" "127.0.0.1:$PORT3" \
    > "$WORK/serve_control.log" 2>&1 &
SERVE_PID=$!
PIDS+=("$SERVE_PID")
if ! wait_up "$PORT3" 240; then
    echo "FAIL: control-leg service never came up; tail of serve log:"
    tail -20 "$WORK/serve_control.log"
    exit 1
fi

python tools/loadgen.py --url "http://127.0.0.1:$PORT3" \
    "${DENSE_ARGS[@]}" --out "$WORK/loadgen_dense_control.json"
python tools/loadgen.py --url "http://127.0.0.1:$PORT3" \
    "${SPARSE_ARGS[@]}" --out "$WORK/loadgen_sparse_control.json"
python tools/loadgen.py --url "http://127.0.0.1:$PORT3" \
    "${SPARSE90_ARGS[@]}" --out "$WORK/loadgen_sparse90_control.json"
drain_quality "$PORT3"
mv "$WORK/slo_snapshot.json" "$WORK/slo_control.json"

set +e
python tools/quality_gate.py QUALITY_BASELINE.json \
    --fresh "$WORK/slo_control.json" \
    > "$WORK/quality_gate_control.json"
CONTROL_RC=$?
set -e
if [ "$CONTROL_RC" -ne 1 ]; then
    echo "FAIL: quality gate rc $CONTROL_RC on the REPORTER_SPARSE=0"
    echo "control (want 1): the baseline no longer enforces the"
    echo "calibrated sparse accuracy"
    cat "$WORK/quality_gate_control.json"
    exit 1
fi
echo "control leg: dense model FAILS the calibrated baseline (rc 1) — the"
echo "gate enforces the recovered sparse accuracy"

echo "quality rehearsal OK (artifacts in $WORK)"
