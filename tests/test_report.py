"""Behavioral tests for report(), mirroring the reference walk
(py/reporter_service.py:79-179) case by case."""

import pytest

from reporter_tpu.report import report
from reporter_tpu.tiles.segment_id import pack_segment_id


def seg(sid=None, start=0.0, end=10.0, length=100.0, internal=False, queue=0, begin=0, end_idx=1):
    s = {
        "start_time": start,
        "end_time": end,
        "length": length,
        "internal": internal,
        "queue_length": queue,
        "begin_shape_index": begin,
        "end_shape_index": end_idx,
        "way_ids": [],
    }
    if sid is not None:
        s["segment_id"] = sid
    return s


def mk_trace(n=10, t0=0, dt=10):
    return {"uuid": "u", "trace": [{"lat": 0, "lon": 0, "time": t0 + i * dt} for i in range(n)]}


L0 = pack_segment_id(0, 1, 1)
L0B = pack_segment_id(0, 1, 2)
L1 = pack_segment_id(1, 1, 3)
L2 = pack_segment_id(2, 1, 4)

RL = {0, 1}
TL = {0, 1}


def test_basic_pair_reporting():
    match = {"segments": [
        seg(L0, start=0, end=30, length=300, begin=0, end_idx=3),
        seg(L0B, start=30, end=60, length=300, begin=3, end_idx=6),
    ]}
    out = report(match, mk_trace(n=10, dt=10), 15, RL, TL)
    reports = out["datastore"]["reports"]
    # only the prior (first) segment is reported; the second awaits a successor
    assert len(reports) == 1
    r = reports[0]
    assert r["id"] == L0 and r["next_id"] == L0B
    assert r["t0"] == 0 and r["t1"] == 30  # t1 = successor start (transition level)
    assert out["stats"]["successful_matches"]["count"] == 1
    assert out["stats"]["successful_matches"]["length"] == 0.3


def test_threshold_holds_back_recent_segments():
    # trace ends at t=90; segment starting at 80 is within threshold 15
    match = {"segments": [
        seg(L0, start=0, end=50, length=300, begin=0, end_idx=5),
        seg(L0B, start=80, end=90, length=300, begin=8, end_idx=9),
    ]}
    out = report(match, mk_trace(n=10, dt=10), 15, RL, TL)
    # the recent segment is excluded entirely: nothing to pair the first with
    assert out["datastore"]["reports"] == []
    assert out.get("shape_used") is None  # begin_shape_index 0 is falsy -> omitted


def test_shape_used_emitted_for_nonzero_index():
    match = {"segments": [
        seg(L0, start=0, end=30, length=300, begin=0, end_idx=3),
        seg(L0B, start=30, end=60, length=300, begin=3, end_idx=6),
    ]}
    out = report(match, mk_trace(n=10, dt=10), 15, RL, TL)
    assert out["shape_used"] == 3


def test_non_transition_level_uses_prior_end_time():
    # successor on level 2, transitions only {0,1}: t1 = prior end, no next_id
    match = {"segments": [
        seg(L0, start=0, end=30, length=300),
        seg(L2, start=35, end=60, length=300),
    ]}
    out = report(match, mk_trace(n=10, dt=10), 15, RL, TL)
    r = out["datastore"]["reports"][0]
    assert r["t1"] == 30 and "next_id" not in r


def test_unreported_level():
    # prior on level 2 with report_levels {0,1}: counted unreported
    match = {"segments": [
        seg(L2, start=0, end=30, length=300),
        seg(L0, start=30, end=60, length=300),
    ]}
    out = report(match, mk_trace(n=10, dt=10), 15, RL, TL)
    assert out["datastore"]["reports"] == []
    assert out["stats"]["unreported_matches"]["count"] == 1
    assert out["stats"]["unreported_matches"]["length"] == 0.3


def test_partial_prior_never_reported():
    match = {"segments": [
        seg(L0, start=-1, end=30, length=-1),
        seg(L0B, start=30, end=60, length=300),
    ]}
    out = report(match, mk_trace(n=10, dt=10), 15, RL, TL)
    assert out["datastore"]["reports"] == []


def test_internal_segment_transparent():
    match = {"segments": [
        seg(L0, start=0, end=28, length=300),
        seg(None, start=28, end=32, length=-1, internal=True),
        seg(L0B, start=32, end=60, length=300),
    ]}
    out = report(match, mk_trace(n=10, dt=10), 15, RL, TL)
    r = out["datastore"]["reports"][0]
    # the internal segment is skipped; pair is (L0, L0B) with t1 = L0B start
    assert r["id"] == L0 and r["next_id"] == L0B and r["t1"] == 32
    # internal does not count as unassociated
    assert out["stats"]["unassociated_segments"] == 0


def test_invalid_time_and_speed_cuts():
    match = {"segments": [
        seg(L0, start=30, end=30, length=300),   # dt = 0 -> invalid time
        seg(L0B, start=30, end=31, length=300),  # prior for next pair
        seg(L1, start=31, end=60, length=300),
    ]}
    out = report(match, mk_trace(n=10, dt=10), 15, RL, TL)
    # pair1: t0=30 t1=30 -> invalid time; pair2: 300m in 1s -> invalid speed
    assert out["stats"]["match_errors"]["invalid_times"] == 1
    assert out["stats"]["match_errors"]["invalid_speeds"] == 1
    assert out["datastore"]["reports"] == []


def test_discontinuity_count():
    match = {"segments": [
        seg(L0, start=0, end=-1, length=-1),
        seg(L0B, start=-1, end=60, length=-1),
        seg(L1, start=60, end=70, length=300),
    ]}
    out = report(match, mk_trace(n=10, dt=10), 15, RL, TL)
    assert out["stats"]["match_errors"]["discontinuities"] == 1


def test_unassociated_count():
    match = {"segments": [
        seg(None, start=0, end=30, length=-1),
        seg(L0, start=30, end=60, length=300),
    ]}
    out = report(match, mk_trace(n=10, dt=10), 15, RL, TL)
    assert out["stats"]["unassociated_segments"] == 1


def test_mode_propagated():
    match = {"segments": []}
    out = report(match, mk_trace(), 15, RL, TL)
    assert out["datastore"]["mode"] == "auto"
    assert out["segment_matcher"]["mode"] == "auto"


def test_unassociated_prior_with_positive_length_not_counted_unreported():
    # reference gate (reporter_service.py:122): prior must have a segment id;
    # a matched-but-unassociated prior with positive length contributes only
    # to unassociated_segments, never to unreported_matches
    match = {"segments": [
        seg(None, start=0, end=30, length=300),
        seg(L0, start=30, end=60, length=300),
    ]}
    out = report(match, mk_trace(n=10, dt=10), 15, RL, TL)
    assert out["stats"]["unreported_matches"]["count"] == 0
    assert out["stats"]["unassociated_segments"] == 1


def test_documented_schema_contract():
    """Every field the reference documents (README.md:269-302) and nothing
    undocumented, through the real matcher end to end — including the
    internal/segment_id exclusivity rule ('internal ... cannot be true if
    segment_id is present')."""
    import numpy as np

    from reporter_tpu.matching import SegmentMatcher
    from reporter_tpu.synth.generator import dryrun_scenario

    cfg, arrays, ubodt = dryrun_scenario(rows=6, cols=6)
    m = SegmentMatcher(arrays=arrays, ubodt=ubodt, config=cfg)
    ax = float(arrays.node_x[arrays.edge_from[0]])
    ay = float(arrays.node_y[arrays.edge_from[0]])
    cx = float(arrays.node_x[arrays.edge_to[7]])
    cy = float(arrays.node_y[arrays.edge_to[7]])
    lat, lon = arrays.proj.to_latlon(np.linspace(ax, cx, 30), np.linspace(ay, cy, 30))
    trace = {
        "uuid": "schema",
        "match_options": {"mode": "auto", "report_levels": [0, 1, 2],
                          "transition_levels": [0, 1, 2]},
        "trace": [{"lat": float(a), "lon": float(o), "time": 1000 + 7 * i}
                  for i, (a, o) in enumerate(zip(lat, lon))],
    }
    out = report(m.match(trace), trace, 15, {0, 1, 2}, {0, 1, 2}, mode="auto")

    ds = out["datastore"]
    assert ds["mode"] == "auto" and ds["reports"]
    for r in ds["reports"]:
        assert set(r) == {"id", "next_id", "queue_length", "length", "t0", "t1"}

    sm = out["segment_matcher"]
    assert sm["mode"] == "auto" and sm["segments"]
    base = {"way_ids", "start_time", "end_time", "queue_length", "length",
            "internal", "begin_shape_index", "end_shape_index"}
    for s in sm["segments"]:
        assert base <= set(s)
        assert not (set(s) - base - {"segment_id"}), "undocumented field"
        if s["internal"]:
            assert "segment_id" not in s
        else:
            assert "segment_id" in s  # non-internal matched coverage carries one

    # the multi-edge drive holds back an in-progress tail segment, so the
    # documented trim index must be PRESENT here, not merely well-typed
    assert isinstance(out["shape_used"], int) and out["shape_used"] > 0

    # internal/segment_id exclusivity on an ACTUAL internal segment (the
    # grid scenario has none, so exercise the association emitter directly)
    intr = {"segments": [
        seg(L0, start=0, end=30, length=300, begin=0, end_idx=3),
        seg(None, start=30, end=40, internal=True, begin=3, end_idx=4),
        seg(L1, start=40, end=70, length=300, begin=4, end_idx=7),
    ]}
    out2 = report(intr, mk_trace(n=10, dt=10), 15, {0, 1}, {0, 1})
    internals = [s for s in out2["segment_matcher"]["segments"] if s["internal"]]
    assert internals and all("segment_id" not in s for s in internals)
    assert "stats" in out and "stats" in out2
