"""Behavioral tests for report(), mirroring the reference walk
(py/reporter_service.py:79-179) case by case."""

import pytest

from reporter_tpu.report import report
from reporter_tpu.tiles.segment_id import pack_segment_id


def seg(sid=None, start=0.0, end=10.0, length=100.0, internal=False, queue=0, begin=0, end_idx=1):
    s = {
        "start_time": start,
        "end_time": end,
        "length": length,
        "internal": internal,
        "queue_length": queue,
        "begin_shape_index": begin,
        "end_shape_index": end_idx,
        "way_ids": [],
    }
    if sid is not None:
        s["segment_id"] = sid
    return s


def mk_trace(n=10, t0=0, dt=10):
    return {"uuid": "u", "trace": [{"lat": 0, "lon": 0, "time": t0 + i * dt} for i in range(n)]}


L0 = pack_segment_id(0, 1, 1)
L0B = pack_segment_id(0, 1, 2)
L1 = pack_segment_id(1, 1, 3)
L2 = pack_segment_id(2, 1, 4)

RL = {0, 1}
TL = {0, 1}


def test_basic_pair_reporting():
    match = {"segments": [
        seg(L0, start=0, end=30, length=300, begin=0, end_idx=3),
        seg(L0B, start=30, end=60, length=300, begin=3, end_idx=6),
    ]}
    out = report(match, mk_trace(n=10, dt=10), 15, RL, TL)
    reports = out["datastore"]["reports"]
    # only the prior (first) segment is reported; the second awaits a successor
    assert len(reports) == 1
    r = reports[0]
    assert r["id"] == L0 and r["next_id"] == L0B
    assert r["t0"] == 0 and r["t1"] == 30  # t1 = successor start (transition level)
    assert out["stats"]["successful_matches"]["count"] == 1
    assert out["stats"]["successful_matches"]["length"] == 0.3


def test_threshold_holds_back_recent_segments():
    # trace ends at t=90; segment starting at 80 is within threshold 15
    match = {"segments": [
        seg(L0, start=0, end=50, length=300, begin=0, end_idx=5),
        seg(L0B, start=80, end=90, length=300, begin=8, end_idx=9),
    ]}
    out = report(match, mk_trace(n=10, dt=10), 15, RL, TL)
    # the recent segment is excluded entirely: nothing to pair the first with
    assert out["datastore"]["reports"] == []
    assert out.get("shape_used") is None  # begin_shape_index 0 is falsy -> omitted


def test_shape_used_emitted_for_nonzero_index():
    match = {"segments": [
        seg(L0, start=0, end=30, length=300, begin=0, end_idx=3),
        seg(L0B, start=30, end=60, length=300, begin=3, end_idx=6),
    ]}
    out = report(match, mk_trace(n=10, dt=10), 15, RL, TL)
    assert out["shape_used"] == 3


def test_non_transition_level_uses_prior_end_time():
    # successor on level 2, transitions only {0,1}: t1 = prior end, no next_id
    match = {"segments": [
        seg(L0, start=0, end=30, length=300),
        seg(L2, start=35, end=60, length=300),
    ]}
    out = report(match, mk_trace(n=10, dt=10), 15, RL, TL)
    r = out["datastore"]["reports"][0]
    assert r["t1"] == 30 and "next_id" not in r


def test_unreported_level():
    # prior on level 2 with report_levels {0,1}: counted unreported
    match = {"segments": [
        seg(L2, start=0, end=30, length=300),
        seg(L0, start=30, end=60, length=300),
    ]}
    out = report(match, mk_trace(n=10, dt=10), 15, RL, TL)
    assert out["datastore"]["reports"] == []
    assert out["stats"]["unreported_matches"]["count"] == 1
    assert out["stats"]["unreported_matches"]["length"] == 0.3


def test_partial_prior_never_reported():
    match = {"segments": [
        seg(L0, start=-1, end=30, length=-1),
        seg(L0B, start=30, end=60, length=300),
    ]}
    out = report(match, mk_trace(n=10, dt=10), 15, RL, TL)
    assert out["datastore"]["reports"] == []


def test_internal_segment_transparent():
    match = {"segments": [
        seg(L0, start=0, end=28, length=300),
        seg(None, start=28, end=32, length=-1, internal=True),
        seg(L0B, start=32, end=60, length=300),
    ]}
    out = report(match, mk_trace(n=10, dt=10), 15, RL, TL)
    r = out["datastore"]["reports"][0]
    # the internal segment is skipped; pair is (L0, L0B) with t1 = L0B start
    assert r["id"] == L0 and r["next_id"] == L0B and r["t1"] == 32
    # internal does not count as unassociated
    assert out["stats"]["unassociated_segments"] == 0


def test_invalid_time_and_speed_cuts():
    match = {"segments": [
        seg(L0, start=30, end=30, length=300),   # dt = 0 -> invalid time
        seg(L0B, start=30, end=31, length=300),  # prior for next pair
        seg(L1, start=31, end=60, length=300),
    ]}
    out = report(match, mk_trace(n=10, dt=10), 15, RL, TL)
    # pair1: t0=30 t1=30 -> invalid time; pair2: 300m in 1s -> invalid speed
    assert out["stats"]["match_errors"]["invalid_times"] == 1
    assert out["stats"]["match_errors"]["invalid_speeds"] == 1
    assert out["datastore"]["reports"] == []


def test_discontinuity_count():
    match = {"segments": [
        seg(L0, start=0, end=-1, length=-1),
        seg(L0B, start=-1, end=60, length=-1),
        seg(L1, start=60, end=70, length=300),
    ]}
    out = report(match, mk_trace(n=10, dt=10), 15, RL, TL)
    assert out["stats"]["match_errors"]["discontinuities"] == 1


def test_unassociated_count():
    match = {"segments": [
        seg(None, start=0, end=30, length=-1),
        seg(L0, start=30, end=60, length=300),
    ]}
    out = report(match, mk_trace(n=10, dt=10), 15, RL, TL)
    assert out["stats"]["unassociated_segments"] == 1


def test_mode_propagated():
    match = {"segments": []}
    out = report(match, mk_trace(), 15, RL, TL)
    assert out["datastore"]["mode"] == "auto"
    assert out["segment_matcher"]["mode"] == "auto"


def test_unassociated_prior_with_positive_length_not_counted_unreported():
    # reference gate (reporter_service.py:122): prior must have a segment id;
    # a matched-but-unassociated prior with positive length contributes only
    # to unassociated_segments, never to unreported_matches
    match = {"segments": [
        seg(None, start=0, end=30, length=300),
        seg(L0, start=30, end=60, length=300),
    ]}
    out = report(match, mk_trace(n=10, dt=10), 15, RL, TL)
    assert out["stats"]["unreported_matches"]["count"] == 0
    assert out["stats"]["unassociated_segments"] == 1
