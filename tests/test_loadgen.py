"""tools/loadgen.py: open-loop schedule generation, session affinity,
archive replay, pacing, artifact schema — and the coordinated-omission
regression: a stalled server must show up in the reported tail because
latencies are measured against the SCHEDULED send time, not the moment a
backlogged client finally got the request out."""

import importlib.util
import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

TOOLS = os.path.join(os.path.dirname(__file__), "..", "tools")


def _load(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(TOOLS, name + ".py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def loadgen():
    return _load("loadgen")


# -- schedule ---------------------------------------------------------------

def test_build_schedule_poisson_and_uniform(loadgen):
    import random

    rng = random.Random(42)
    sched = loadgen.build_schedule(2000, 50.0, "poisson", rng)
    assert len(sched) == 2000
    assert all(b > a for a, b in zip(sched, sched[1:]))
    # mean inter-arrival ~ 1/rate (law of large numbers, seeded)
    assert sched[-1] / 2000 == pytest.approx(1 / 50.0, rel=0.15)
    # deterministic under the same seed
    assert sched == loadgen.build_schedule(2000, 50.0, "poisson",
                                           random.Random(42))
    uni = loadgen.build_schedule(10, 10.0, "uniform", rng)
    assert uni == pytest.approx([0.1 * (i + 1) for i in range(10)])
    with pytest.raises(ValueError):
        loadgen.build_schedule(5, 0.0, "poisson", rng)


def test_interleave_preserves_session_order(loadgen):
    sessions = [
        ("a", [{"uuid": "a", "w": 0}, {"uuid": "a", "w": 1}, {"uuid": "a", "w": 2}]),
        ("b", [{"uuid": "b", "w": 0}]),
        ("c", [{"uuid": "c", "w": 0}, {"uuid": "c", "w": 1}]),
    ]
    flat = loadgen.interleave(sessions)
    assert len(flat) == 6
    for uuid in "abc":
        ws = [r["w"] for r in flat if r["uuid"] == uuid]
        assert ws == sorted(ws), "uuid affinity: windows out of order"


def test_archive_sessions_and_time_warp(loadgen, tmp_path):
    rows = []
    for veh in ("v1", "v2"):
        for i in range(6):
            t = 1000 + i * 30 + (500 if veh == "v2" else 0)
            rows.append("%s|%d|37.75%d|-122.44%d|5" % (veh, t, i, i))
    (tmp_path / "part.csv").write_text("\n".join(rows) + "\n")
    sessions = loadgen.archive_sessions(
        str(tmp_path), "|", 0, 1, 2, 3, window=3)
    assert [u for u, _r in sessions] == ["v1", "v2"]
    for _u, reqs in sessions:
        assert all(len(r["trace"]) >= 2 for r in reqs)
        t0s = [r["_t0"] for r in reqs]
        assert t0s == sorted(t0s)
    reqs = loadgen.interleave(sessions)
    sched = loadgen.timeline_schedule(reqs, warp=10.0)
    # original span: v1 t0=1000 .. v2 last-window t0=1590 -> 59 s warped
    assert sched[0] == 0.0
    assert sched[-1] == pytest.approx((1590 - 1000) / 10.0)
    assert all(b >= a for a, b in zip(sched, sched[1:]))
    # requests were reordered onto the warped timeline
    assert reqs[0]["_t0"] == 1000


def test_synth_sessions_shape(loadgen):
    sessions = loadgen.synth_sessions(vehicles=3, points=8, window=4,
                                      grid=5, seed=1)
    assert len(sessions) == 3
    for uuid, reqs in sessions:
        assert uuid.startswith("loadgen-veh-")
        for r in reqs:
            assert r["uuid"] == uuid and len(r["trace"]) >= 2
            assert r["match_options"]["report_levels"] == [0, 1]


# -- make_requests pacing ---------------------------------------------------

def test_make_requests_paced_rate_and_limit():
    mr = _load("make_requests")
    sleeps = []
    clock = {"t": 0.0}

    def fake_clock():
        return clock["t"]

    def fake_sleep(s):
        sleeps.append(s)
        clock["t"] += s

    out = list(mr.paced(iter(range(5)), rate=10.0, limit=0,
                        clock=fake_clock, sleep=fake_sleep))
    assert out == [0, 1, 2, 3, 4]
    # open-loop metronome: record i released at t0 + i/rate
    assert sleeps == pytest.approx([0.1, 0.1, 0.1, 0.1])
    # a slow consumer gets NO extra sleeps (backlog, not rate decay)
    sleeps.clear()
    clock["t"] = 0.0

    def slow_consume():
        for i, rec in enumerate(mr.paced(iter(range(4)), rate=10.0,
                                         clock=fake_clock, sleep=fake_sleep)):
            clock["t"] += 0.25  # consumer burns 250 ms per record
            yield rec

    assert list(slow_consume()) == [0, 1, 2, 3]
    assert sleeps == []  # always behind schedule: paced never sleeps
    # limit stops the stream
    assert list(mr.paced(iter(range(100)), rate=0.0, limit=3)) == [0, 1, 2]


def test_make_requests_cli_rate_limit(tmp_path, capsys):
    mr = _load("make_requests")
    src = tmp_path / "probes.csv"
    src.write_text("\n".join(
        "veh-%d|%d|37.75|-122.44|5" % (i, 1000 + i) for i in range(10)) + "\n")
    rc = mr.main(["--src", str(src), "--salt", "s1", "--dry-run",
                  "--limit", "4", "--rate", "1000"])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 4
    assert all("veh-" not in line for line in out), "uuid not salted"


# -- a controllable stub server ---------------------------------------------

class _Stub:
    """Single-threaded HTTP stub: requests serialize, per-request delay is
    scriptable, and the status code is switchable — the deterministic
    stand-in for a stalled serving tier."""

    def __init__(self, delays=(), code=200):
        stub = self

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):
                n = self.headers.get("Content-Length")
                if n:
                    self.rfile.read(int(n))
                i = stub.count
                stub.count += 1
                if i < len(stub.delays):
                    time.sleep(stub.delays[i])
                body = json.dumps({"ok": True}).encode()
                self.send_response(stub.code)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                body = json.dumps({"status": "ok", "backend": "stub",
                                   "edges": 80}).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):
                pass

        self.count = 0
        self.delays = list(delays)
        self.code = code
        self.httpd = HTTPServer(("127.0.0.1", 0), Handler)
        self.url = "http://127.0.0.1:%d" % self.httpd.server_port
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()

    def close(self):
        self.httpd.shutdown()


# -- the coordinated-omission regression ------------------------------------

def test_scheduled_time_latency_not_response_gap(loadgen):
    """One 0.8 s stall at the head of a 50 req/s schedule with a
    single-connection client: every later request is SENT late, and the
    reported (scheduled-time) latency must carry that backlog while the
    send-to-response gap stays flat — the exact lie a closed-loop
    generator would tell."""
    stub = _Stub(delays=[0.8])
    try:
        reqs = [{"uuid": "v", "trace": [], "match_options": {}}] * 10
        sched = [i / 50.0 for i in range(10)]
        samples, _t0 = loadgen.run_load(stub.url + "/report", reqs, sched,
                                        concurrency=1, timeout_s=10.0)
    finally:
        stub.close()
    assert len(samples) == 10
    assert all(s.code == 200 for s in samples)
    late = samples[1:]
    # the flattering number: every post-stall response came back fast
    assert max(s.service_s for s in late) < 0.4
    # the honest number: the backlog rides the scheduled-time latency
    assert min(s.latency_s for s in late) > 0.4
    q_sched = loadgen.quantiles_ms([s.latency_s for s in samples])
    q_gap = loadgen.quantiles_ms([s.service_s for s in samples])
    assert q_sched["p50_ms"] > 400 > q_gap["p50_ms"]
    # and the artifact stats carry BOTH, so omission is falsifiable
    st = loadgen.step_stats(samples, 50.0)
    assert st["quantiles"]["p50_ms"] > st["service_time_quantiles"]["p50_ms"]
    assert st["max_send_lag_s"] > 0.4


def test_loadgen_reports_device_hang_tail(loadgen, monkeypatch):
    """The ISSUE-pinned regression: loadgen against a real service with a
    faults.py device_hang must report scheduled-time latencies — the
    injected stall visibly degrades the reported tail even though each
    individual post-stall response is fast."""
    import numpy as np

    from reporter_tpu import faults
    from reporter_tpu.matching import MatcherConfig, SegmentMatcher
    from reporter_tpu.serve import ReporterService
    from reporter_tpu.tiles.arrays import build_graph_arrays
    from reporter_tpu.tiles.network import grid_city
    from reporter_tpu.tiles.ubodt import build_ubodt

    city = grid_city(rows=5, cols=5, spacing_m=150.0)
    arrays = build_graph_arrays(city, cell_size=100.0)
    ubodt = build_ubodt(arrays, delta=2000.0)
    matcher = SegmentMatcher(arrays=arrays, ubodt=ubodt,
                             config=MatcherConfig())
    service = ReporterService(matcher, max_wait_ms=5.0)
    httpd = service.make_server("127.0.0.1", 0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    url = "http://127.0.0.1:%d" % httpd.server_port

    nodes = [2 * 5 + c for c in range(5)]
    t = np.linspace(0.05, 0.9, 6)
    xs = np.interp(t, np.linspace(0, 1, 5), arrays.node_x[nodes])
    ys = np.interp(t, np.linspace(0, 1, 5), arrays.node_y[nodes])
    lat, lon = arrays.proj.to_latlon(xs, ys)
    body = {
        "uuid": "veh-hang",
        "trace": [{"lat": float(a), "lon": float(o), "time": 1000 + 15 * i}
                  for i, (a, o) in enumerate(zip(lat, lon))],
        "match_options": {"mode": "auto", "report_levels": [0, 1],
                          "transition_levels": [0, 1]},
    }
    try:
        # warm the dispatch path BEFORE arming the fault so compile time
        # doesn't masquerade as the hang
        matcher.match_many([dict(body)])
        monkeypatch.setenv("REPORTER_FAULT_DEVICE_HANG", "0.7:2")
        faults.reset()
        reqs = [dict(body) for _ in range(15)]
        sched = [i / 30.0 for i in range(15)]
        samples, _t0 = loadgen.run_load(url + "/report", reqs, sched,
                                        concurrency=2, timeout_s=30.0)
    finally:
        httpd.shutdown()
        monkeypatch.delenv("REPORTER_FAULT_DEVICE_HANG", raising=False)
        faults.reset()
    assert len(samples) == 15
    assert all(s.code == 200 for s in samples)
    q_sched = loadgen.quantiles_ms([s.latency_s for s in samples])
    q_gap = loadgen.quantiles_ms([s.service_s for s in samples])
    # the two injected 0.7 s hangs are visible in the scheduled-time tail
    assert q_sched["p95_ms"] > 700
    # and strictly exceed the response-gap view (the backlog is real)
    assert q_sched["p95_ms"] > q_gap["p95_ms"]
    assert max(s.sent - s.sched for s in samples) > 0.4


# -- artifact + verdict semantics -------------------------------------------

def test_main_artifact_schema_and_perf_gate_consumable(loadgen, tmp_path):
    stub = _Stub()
    out = tmp_path / "loadgen.json"
    try:
        rc = loadgen.main([
            "--url", stub.url, "--rate", "60", "--duration", "0.4",
            "--vehicles", "2", "--points", "6", "--window", "3",
            "--grid", "5", "--seed", "3", "--concurrency", "8",
            "--slo-availability", "0.5", "--slo-p99-ms", "60000",
            "--out", str(out),
        ])
    finally:
        stub.close()
    assert rc == 0
    art = json.loads(out.read_text())
    # perf_gate header keys (docs/bench-schema.md shape)
    for key in ("metric", "value", "unit", "platform", "attrib",
                "last_onchip", "attrib_reason"):
        assert key in art, key
    assert art["edges"] == 80  # picked up from /health
    assert art["requests"] >= 1 and art["status"].get("200")
    assert art["slo"]["client"]["ok"] is True
    assert art["quantiles"]["p99_ms"] is not None
    # the artifact passes the real perf gate (like-provenance aware)
    pg = _load("perf_gate")
    repo = os.path.join(os.path.dirname(__file__), "..")
    history = sorted(
        os.path.join(repo, f) for f in os.listdir(repo)
        if f.startswith("BENCH_r0") and f.endswith(".json"))
    rc2, verdict = pg.gate(history, fresh=str(out), require_attrib=True)
    assert rc2 == 0, verdict


def test_main_rc_nonzero_on_slo_violation(loadgen, tmp_path):
    stub = _Stub(code=500)
    out = tmp_path / "loadgen.json"
    try:
        rc = loadgen.main([
            "--url", stub.url, "--rate", "50", "--duration", "0.2",
            "--vehicles", "1", "--points", "4", "--window", "2",
            "--grid", "5", "--slo-availability", "0.9",
            "--slo-p99-ms", "60000", "--out", str(out),
        ])
    finally:
        stub.close()
    assert rc == 1
    art = json.loads(out.read_text())
    assert art["slo"]["client"]["ok"] is False
    assert art["status"].get("500")


# -- streaming scenario (docs/performance.md "The session matcher") ---------


def test_stream_points_per_point_corpus(loadgen):
    sessions = [("a", [{"uuid": "a", "trace": [{"t": i} for i in range(4)]}]),
                ("b", [{"uuid": "b", "trace": [{"t": i} for i in range(2)]}])]
    pts = loadgen.stream_points(sessions)
    assert len(pts) == 6
    assert all(r["stream"] is True and len(r["trace"]) == 1 for r in pts)
    for uuid, n in (("a", 4), ("b", 2)):
        ts = [r["trace"][0]["t"] for r in pts if r["uuid"] == uuid]
        assert ts == list(range(n)), "per-uuid point order broken"


def test_fold_stream_windows_per_point_scheds(loadgen):
    """The windowed-rebatch baseline: requests fold per-uuid at the SAME
    per-point schedule, each point keeping its own arrival slot in
    _scheds, windows sent at their LAST point's slot, <2-point tails
    dropped and counted."""
    pts = []
    sched = []
    for k in range(5):  # a:3 points then a:2 more; b:2 points total
        for uuid in ("a", "b")[: 2 if k < 2 else 1]:
            pts.append({"uuid": uuid, "stream": True,
                        "trace": [{"t": k}],
                        "match_options": {}})
            sched.append(0.1 * len(sched))
    reqs, out_sched, dropped = loadgen.fold_stream_windows(pts, sched, 2)
    # a had 5 points -> two 2-windows + 1 dropped tail; b had 2 -> one
    assert dropped == 1
    assert len(reqs) == 3 and out_sched == sorted(out_sched)
    for r, s in zip(reqs, out_sched):
        assert "stream" not in r  # the baseline is the CLASSIC windowed path
        assert len(r["trace"]) == 2
        assert len(r["_scheds"]) == 2
        assert s == r["_scheds"][-1]  # sent at the last point's slot


def test_main_stream_scenario_per_point_samples(loadgen, tmp_path):
    """--stream end to end against the stub: every POINT lands as one
    sample (stream mode) and the artifact carries the stream block +
    scenario-specific metric name."""
    stub = _Stub()
    out = tmp_path / "stream.json"
    try:
        rc = loadgen.main([
            "--url", stub.url, "--stream", "--rate", "60",
            "--duration", "0.4", "--vehicles", "2", "--points", "6",
            "--window", "6", "--grid", "5", "--seed", "3",
            "--concurrency", "8", "--slo-availability", "0.5",
            "--slo-p99-ms", "60000", "--out", str(out),
        ])
    finally:
        stub.close()
    assert rc == 0
    art = json.loads(out.read_text())
    assert art["mode"] == "stream"
    assert art["metric"] == "loadgen_stream_p99_latency"
    assert art["stream"] == {"window": 1, "points": art["requests"],
                             "points_dropped_tail": 0}
    # one HTTP request per point: the stub counted exactly the samples
    assert stub.count == art["requests"]

    # the windowed-rebatch baseline: HTTP requests fold ~window-fold but
    # SAMPLES stay per-point, so the quantiles compare like with like
    stub2 = _Stub()
    out2 = tmp_path / "windowed.json"
    try:
        rc = loadgen.main([
            "--url", stub2.url, "--stream", "--stream-window", "3",
            "--rate", "60", "--duration", "0.4", "--vehicles", "2",
            "--points", "6", "--window", "6", "--grid", "5", "--seed", "3",
            "--concurrency", "8", "--slo-availability", "0.5",
            "--slo-p99-ms", "60000", "--out", str(out2),
        ])
    finally:
        stub2.close()
    assert rc == 0
    art2 = json.loads(out2.read_text())
    assert art2["mode"] == "stream-windowed"
    assert art2["metric"] == "loadgen_stream_windowed_p99_latency"
    assert art2["requests"] + art2["stream"]["points_dropped_tail"] \
        == art["requests"]
    assert stub2.count < stub.count  # fewer wire requests, same points
    # the baseline's per-point latency includes the window-fill wait, so
    # its p50 must exceed the per-point path's against the same stub
    assert art2["quantiles"]["p50_ms"] > art["quantiles"]["p50_ms"]


def test_profile_schedules_flash_and_diurnal(loadgen):
    import random

    rng = random.Random(1)
    # flash: the burst window carries ~mult x the baseline arrival rate
    s = loadgen.profile_schedule(20.0, 10.0, "flash:0.3:0.7:5",
                                 "poisson", rng)
    mid = sum(1 for t in s if 3.0 <= t < 7.0) / 4.0
    edge = sum(1 for t in s if t < 3.0 or t >= 7.0) / 6.0
    assert mid > 3.0 * edge
    assert all(0.0 <= t < 10.0 for t in s)
    # diurnal: a deterministic (uniform) schedule starts at the trough
    # (sparse arrivals) and peaks mid-run (dense arrivals)
    d = loadgen.profile_schedule(20.0, 10.0, "diurnal", "uniform", rng)
    gaps_start = d[1] - d[0]
    mid_i = min(range(len(d)), key=lambda i: abs(d[i] - 5.0))
    gaps_mid = d[mid_i + 1] - d[mid_i]
    assert gaps_mid < gaps_start / 2.0
    with pytest.raises(ValueError):
        loadgen.profile_rate_fn("flash:bad", 1.0, 1.0)
    with pytest.raises(ValueError):
        loadgen.profile_rate_fn("nope", 1.0, 1.0)


def test_skewed_requests_concentrate_and_preserve_order(loadgen):
    import random

    rng = random.Random(2)
    per_uuid = [("veh-%d" % i,
                 [{"uuid": "veh-%d" % i, "trace": [j]} for j in range(4)])
                for i in range(10)]
    reqs = loadgen.skewed_requests(per_uuid, 400, share=0.8,
                                   hot_frac=0.1, rng=rng, stream=False)
    assert len(reqs) == 400
    counts = {}
    for r in reqs:
        counts[r["uuid"]] = counts.get(r["uuid"], 0) + 1
    # ~80% of traffic on the single hot vehicle (hot_frac 0.1 of 10)
    assert counts["veh-0"] > 0.6 * 400
    # per-vehicle order preserved within each recycle
    for u in counts:
        seq = [r["trace"][0] for r in reqs if r["uuid"] == u]
        for k in range(1, len(seq)):
            assert seq[k] == (seq[k - 1] + 1) % 4
    # stream recycles rename the uuid so an open session never rewinds
    sreqs = loadgen.skewed_requests(
        [("veh-s", [{"uuid": "veh-s", "trace": [0]},
                    {"uuid": "veh-s", "trace": [1]}])],
        5, share=1.0, hot_frac=1.0, rng=rng, stream=True)
    assert [r["uuid"] for r in sreqs] == [
        "veh-s", "veh-s", "veh-s~c1", "veh-s~c1", "veh-s~c2"]


def test_step_stats_admitted_view(loadgen):
    mk = loadgen.Sample
    samples = [mk(0.0, 0.0, 0.1, 200, False)] * 8 + \
              [mk(0.0, 0.0, 0.01, 429, False)] * 2
    st = loadgen.step_stats(samples, offered_rate=10.0)
    assert st["shed_fraction"] == pytest.approx(0.2)
    assert st["admitted_quantiles"]["p99_ms"] is not None
    # the admitted tail excludes the fast sheds entirely
    assert st["admitted_quantiles"]["p50_ms"] > 50.0
