"""tools/demand_export.py — recorded demand history back into a
replayable loadgen schedule.

Pins the export math (offered vs admitted signal, mean normalization,
span header), the CLI (ring file in, schedule file out, unusable-input
exit codes), and the ROUND TRIP: a recorded diurnal shape exported and
fed back through ``loadgen --profile schedule:<file>`` must realize the
same mean rate and the same shape, within tolerance."""

import importlib.util
import json
import os
import random

import pytest

from reporter_tpu.obs.economics import DemandHistory


def _load(name):
    path = os.path.join(os.path.dirname(__file__), "..", "tools",
                        name + ".py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def de():
    return _load("demand_export")


@pytest.fixture(scope="module")
def lg():
    return _load("loadgen")


def _recs(rates, t0=1000.0, shed=0.0):
    return [{"t": t0 + i, "admitted_rps": r, "shed_rps": shed}
            for i, r in enumerate(rates)]


# -- export math -------------------------------------------------------------

def test_export_normalizes_around_mean(de):
    sched = de.export_schedule(_recs([10.0, 20.0, 30.0]))
    assert sched["base_rate"] == pytest.approx(20.0)
    assert sched["span_s"] == pytest.approx(2.0)
    assert sched["points"] == [[0.0, 0.5], [1.0, 1.0], [2.0, 1.5]]


def test_export_offered_includes_shed(de):
    sched = de.export_schedule(_recs([10.0, 10.0], shed=10.0))
    assert sched["base_rate"] == pytest.approx(20.0)
    admitted = de.export_schedule(_recs([10.0, 10.0], shed=10.0),
                                  signal="admitted")
    assert admitted["base_rate"] == pytest.approx(10.0)


def test_export_skips_malformed_records(de):
    recs = _recs([10.0, 20.0]) + [{"no_t": True}, {"t": 1500.0}]
    sched = de.export_schedule(recs)
    assert sched["records"] == 2


def test_export_rejects_empty_and_zero_demand(de):
    with pytest.raises(ValueError):
        de.export_schedule([])
    with pytest.raises(ValueError):
        de.export_schedule(_recs([0.0, 0.0, 0.0]))


# -- CLI ---------------------------------------------------------------------

def test_cli_ring_to_schedule_file(de, tmp_path):
    ring = str(tmp_path / "rep-0.jsonl")
    h = DemandHistory(ring)
    for r in _recs([5.0, 10.0, 15.0]):
        h.append(r)
    h.close()
    out = str(tmp_path / "sched.json")
    assert de.main(["--history", ring, "--out", out]) == 0
    sched = json.load(open(out))
    assert sched["base_rate"] == pytest.approx(10.0)
    assert len(sched["points"]) == 3


def test_cli_unusable_input_is_rc2(de, tmp_path):
    empty = str(tmp_path / "empty.jsonl")
    open(empty, "w").close()
    assert de.main(["--history", empty, "--out",
                    str(tmp_path / "x.json")]) == 2


# -- the round trip ----------------------------------------------------------

def test_roundtrip_recorded_diurnal_replays_within_tolerance(
        de, lg, tmp_path):
    """Record a diurnal day as history ticks, export, and replay through
    loadgen's own profile machinery: the realized arrival rate must
    match the recorded series in mean (2%) and in shape (the replayed
    peak/trough land where the recording put them)."""
    duration = 120.0
    base_rate = 40.0
    recorded_fn = lg.profile_rate_fn("diurnal", base_rate, duration)
    ticks = [recorded_fn(t) for t in range(int(duration))]
    ring = str(tmp_path / "diurnal.jsonl")
    h = DemandHistory(ring)
    for i, r in enumerate(ticks):
        h.append({"t": 2000.0 + i, "admitted_rps": r, "shed_rps": 0.0})
    h.close()

    out = str(tmp_path / "sched.json")
    assert de.main(["--history", ring, "--out", out]) == 0
    sched = json.load(open(out))

    # replay at the recorded span: with duration == span the stretch is
    # the identity and the sampled points line up with the recorded ticks
    span = sched["span_s"]
    replay_fn = lg.profile_rate_fn("schedule:" + out, sched["base_rate"],
                                   span)
    replayed = [replay_fn(t) for t in range(int(duration))]
    rec_mean = sum(ticks) / len(ticks)
    rep_mean = sum(replayed) / len(replayed)
    assert rep_mean == pytest.approx(rec_mean, rel=0.02)
    # shape: peak and trough land on the recorded positions
    assert replayed.index(max(replayed)) == pytest.approx(
        ticks.index(max(ticks)), abs=2)
    assert replayed.index(min(replayed)) == pytest.approx(
        ticks.index(min(ticks)), abs=2)
    # pointwise shape agreement away from the interpolation seams
    for i in range(0, int(duration), 10):
        assert replayed[i] == pytest.approx(ticks[i], rel=0.05)

    # and the schedule actually drives arrivals: realized admitted rate
    # from the generated schedule matches the recording's mean
    arrivals = lg.profile_schedule(sched["base_rate"], span,
                                   "schedule:" + out, "poisson",
                                   random.Random(7))
    realized = len(arrivals) / span
    assert realized == pytest.approx(rec_mean, rel=0.15)
