"""Real-map ingestion tests: OSM fixtures -> RoadNetwork -> end-to-end match.

The reference's map data arrives as Valhalla planet tiles
(Dockerfile:9-11, py/download_tiles.sh); this framework ingests OSM
extracts directly (tiles/osm.py).  The fixture below is a hand-modelled
city district using real OSM tagging conventions -- motorway + ramps
(_link => internal), primary/secondary/residential levels, one-way streets
(incl. oneway=-1), a roundabout, mph maxspeeds -- written through the
module's own PBF encoder and the XML form, then imported, tiled, matched.
"""

import json
import math
import os

import numpy as np
import pytest

from reporter_tpu.tiles import osm
from reporter_tpu.tiles.osm import OsmWay
from reporter_tpu.tiles.segment_id import get_tile_level


def city_fixture():
    """(nodes, ways): a small district with every classification feature."""
    nodes = {}
    nid = [100]

    def node(lat, lon):
        nid[0] += 1
        nodes[nid[0]] = (lat, lon)
        return nid[0]

    lat0, lon0 = 47.6060, -122.3320  # downtown-ish coordinates
    dg = 0.0015  # ~166 m in latitude

    # residential grid 6x6 with a primary avenue and a secondary cross street
    grid = [[node(lat0 + r * dg, lon0 + c * dg) for c in range(6)] for r in range(6)]
    ways = []
    wid = [1000]

    def way(refs, **tags):
        wid[0] += 1
        ways.append(OsmWay(id=wid[0], refs=list(refs), tags={k: str(v) for k, v in tags.items()}))
        return wid[0]

    for r in range(6):
        tags = {"highway": "residential", "name": "R%d St" % r}
        if r == 2:
            tags = {"highway": "primary", "name": "Central Ave", "maxspeed": "40 mph"}
        if r == 4:
            tags = {"highway": "residential", "oneway": "yes"}
        way(grid[r], **tags)
    for c in range(6):
        tags = {"highway": "residential"}
        if c == 3:
            tags = {"highway": "secondary", "maxspeed": "50"}
        if c == 1:
            tags = {"highway": "residential", "oneway": "-1"}
        way([grid[r][c] for r in range(6)], **tags)

    # motorway along the east edge with on/off ramps (internal links)
    m = [node(lat0 - dg + k * 2 * dg, lon0 + 6.5 * dg) for k in range(4)]
    way(m, highway="motorway", maxspeed="60 mph", name="I-5")
    way([grid[2][5], m[1]], highway="motorway_link")
    way([m[2], grid[4][5]], highway="motorway_link")

    # roundabout at the south-west corner
    clat, clon = lat0 - 2 * dg, lon0 + dg
    ring = [
        node(clat + 0.0004 * math.cos(a), clon + 0.0004 * math.sin(a))
        for a in np.linspace(0, 2 * math.pi, 7)[:-1]
    ]
    way(ring + [ring[0]], highway="tertiary", junction="roundabout")
    way([grid[0][1], ring[0]], highway="tertiary")

    # an unroutable way that must be dropped
    way([grid[0][0], grid[0][1]], highway="footpath")
    way([grid[5][4], grid[5][5]], highway="primary", area="yes")
    return nodes, ways


@pytest.fixture(scope="module")
def fixture_paths(tmp_path_factory):
    d = tmp_path_factory.mktemp("osm")
    nodes, ways = city_fixture()
    pbf = str(d / "city.osm.pbf")
    xml = str(d / "city.osm.xml")
    ovp = str(d / "city.json")
    osm.write_pbf(pbf, nodes, ways)
    with open(xml, "w") as f:
        f.write("<osm version='0.6'>\n")
        for nid, (lat, lon) in nodes.items():
            f.write("<node id='%d' lat='%.9f' lon='%.9f'/>\n" % (nid, lat, lon))
        for w in ways:
            f.write("<way id='%d'>" % w.id)
            for r in w.refs:
                f.write("<nd ref='%d'/>" % r)
            for k, v in w.tags.items():
                f.write("<tag k='%s' v='%s'/>" % (k, v))
            f.write("</way>\n")
        f.write("</osm>\n")
    with open(ovp, "w") as f:
        json.dump({
            "elements": [
                {"type": "node", "id": nid, "lat": lat, "lon": lon}
                for nid, (lat, lon) in nodes.items()
            ] + [
                {"type": "way", "id": w.id, "nodes": w.refs, "tags": w.tags}
                for w in ways
            ]
        }, f)
    return {"pbf": pbf, "xml": xml, "json": ovp, "nodes": nodes, "ways": ways}


def test_pbf_round_trip(fixture_paths):
    nodes, ways = osm.read_pbf(fixture_paths["pbf"])
    assert len(nodes) == len(fixture_paths["nodes"])
    for nid, (lat, lon) in fixture_paths["nodes"].items():
        glat, glon = nodes[nid]
        # 100-nanodegree granularity => < 1 cm
        assert abs(glat - lat) < 1e-6 and abs(glon - lon) < 1e-6
    assert len(ways) == len(fixture_paths["ways"])
    by_id = {w.id: w for w in ways}
    for w in fixture_paths["ways"]:
        got = by_id[w.id]
        assert got.refs == w.refs
        assert got.tags == w.tags


def test_pbf_reader_rejects_corruption_cleanly(fixture_paths, tmp_path):
    """Truncations and bit flips anywhere in a .pbf must raise a normal
    exception (or, for tail truncation of optional data, return partial
    results) -- never hang, crash the interpreter, or allocate wildly.
    The wire codec is hand-rolled (varints, zigzag, deflate blobs), so
    every malformed length/tag path matters."""
    blob = open(fixture_paths["pbf"], "rb").read()
    rng = __import__("numpy").random.default_rng(3)

    cases = []
    # truncations at awkward offsets, including mid-varint
    for frac in (0.05, 0.33, 0.5, 0.9, 0.99):
        cases.append(blob[: int(len(blob) * frac)])
    # single-byte corruptions sprayed across the file
    for _ in range(20):
        b = bytearray(blob)
        b[int(rng.integers(0, len(blob)))] ^= 0xFF
        cases.append(bytes(b))
    # garbage prefixes
    cases.append(b"\xff" * 64 + blob)
    cases.append(b"")

    for i, payload in enumerate(cases):
        p = tmp_path / ("bad_%d.pbf" % i)
        p.write_bytes(payload)
        try:
            nodes, ways = osm.read_pbf(str(p))
            # accepted: a clean partial/equal parse (tail truncation or a
            # flip inside string tables can be survivable)
            assert len(nodes) <= len(fixture_paths["nodes"]) * 2
        except Exception as e:  # noqa: BLE001 - any ordinary exception is a pass
            assert not isinstance(e, (SystemExit, KeyboardInterrupt, MemoryError))


def test_readers_agree(fixture_paths):
    n_pbf, w_pbf = osm.read_pbf(fixture_paths["pbf"])
    n_xml, w_xml = osm.read_xml(fixture_paths["xml"])
    n_js, w_js = osm.read_overpass_json(fixture_paths["json"])
    assert set(n_pbf) == set(n_xml) == set(n_js)
    assert [w.id for w in w_pbf] == [w.id for w in w_xml] == [w.id for w in w_js]
    assert {w.id: w.tags for w in w_xml} == {w.id: w.tags for w in w_js}


def test_classification(fixture_paths):
    net = osm.network_from_file(fixture_paths["pbf"])
    assert net.num_edges > 0
    levels = {e.level for e in net.edges}
    assert levels == {0, 1, 2}
    # motorway is implied-oneway: no reverse edge between consecutive
    # motorway nodes
    mw_ids = {w.id for w in fixture_paths["ways"] if w.tags.get("highway") == "motorway"}
    m_edges = [e for e in net.edges if e.way_id in mw_ids]
    assert m_edges
    pairs = {(e.from_node, e.to_node) for e in m_edges}
    assert all((b, a) not in pairs for a, b in pairs)
    # ramps + roundabout are internal and carry no segment id
    internals = [e for e in net.edges if e.internal]
    assert internals and all(e.segment_id is None for e in internals)
    # every non-internal edge has a packed id whose low bits match its level
    for e in net.edges:
        if not e.internal:
            assert e.segment_id is not None
            assert get_tile_level(e.segment_id) == e.level
    # mph conversion: Central Ave (primary => level 0) 40 mph ~= 64.4 km/h
    central = [e for e in net.edges if e.level == 0 and abs(e.speed_kph - 64.4) < 0.1]
    assert central
    # dropped ways: no footpath, no area
    assert all(e.speed_kph > 0 for e in net.edges)


def test_oneway_directions(fixture_paths):
    nodes, ways = osm.read_pbf(fixture_paths["pbf"])
    net = osm.network_from_osm(nodes, ways)
    fwd_way = next(w for w in ways if w.tags.get("oneway") == "yes")
    rev_way = next(w for w in ways if w.tags.get("oneway") == "-1")
    fwd_edges = [e for e in net.edges if e.way_id == fwd_way.id]
    rev_edges = [e for e in net.edges if e.way_id == rev_way.id]
    assert fwd_edges and rev_edges
    # forward oneway: edge direction follows ref order
    order = {r: i for i, r in enumerate(fwd_way.refs)}
    for e in fwd_edges:
        la, lo = net.node_lat[e.from_node], net.node_lon[e.from_node]
        # find matching osm node by coordinates
        src = min(nodes, key=lambda n: abs(nodes[n][0] - la) + abs(nodes[n][1] - lo))
        lb, lb2 = net.node_lat[e.to_node], net.node_lon[e.to_node]
        dst = min(nodes, key=lambda n: abs(nodes[n][0] - lb) + abs(nodes[n][1] - lb2))
        assert order[src] < order[dst]
    order = {r: i for i, r in enumerate(rev_way.refs)}
    for e in rev_edges:
        la, lo = net.node_lat[e.from_node], net.node_lon[e.from_node]
        src = min(nodes, key=lambda n: abs(nodes[n][0] - la) + abs(nodes[n][1] - lo))
        lb, lb2 = net.node_lat[e.to_node], net.node_lon[e.to_node]
        dst = min(nodes, key=lambda n: abs(nodes[n][0] - lb) + abs(nodes[n][1] - lb2))
        assert order[src] > order[dst]


def test_rptt_tiles_round_trip(fixture_paths, tmp_path):
    from reporter_tpu.tiles.codec import load_network_tiles, save_network_tiles

    net = osm.network_from_file(fixture_paths["pbf"])
    manifest = save_network_tiles(net, str(tmp_path / "tiles"))
    assert manifest["tiles"]
    back = load_network_tiles(str(tmp_path / "tiles"))
    assert back.num_nodes == net.num_nodes
    assert back.num_edges == net.num_edges
    assert sorted(
        (e.from_node, e.to_node, e.segment_id) for e in back.edges
    ) == sorted((e.from_node, e.to_node, e.segment_id) for e in net.edges)


def test_end_to_end_match_on_imported_city(fixture_paths):
    """VERDICT r01 #3 'done' criterion: synthetic traces over a graph that
    came in through the real-data path, matched end to end, agreement
    reported."""
    from reporter_tpu.matching import MatcherConfig, SegmentMatcher
    from reporter_tpu.synth import TraceSynthesizer
    from reporter_tpu.synth.generator import segment_agreement
    from reporter_tpu.tiles.arrays import build_graph_arrays
    from reporter_tpu.tiles.ubodt import build_ubodt

    net = osm.network_from_file(fixture_paths["pbf"])
    arrays = build_graph_arrays(net, cell_size=100.0)
    ubodt = build_ubodt(arrays, delta=1500.0)
    matcher = SegmentMatcher(arrays=arrays, ubodt=ubodt, config=MatcherConfig())
    synth = TraceSynthesizer(arrays, seed=11)
    straces = synth.batch(12, 40, dt=5.0, sigma=4.0, max_tries=300)
    results = matcher.match_many([s.trace for s in straces])
    assert sum(1 for r in results if r["segments"]) >= 10

    import jax
    import jax.numpy as jnp

    from reporter_tpu.ops.viterbi import match_batch

    B, T = len(straces), 40
    px = np.zeros((B, T), np.float32)
    py = np.zeros((B, T), np.float32)
    tm = np.zeros((B, T), np.float32)
    for i, s in enumerate(straces):
        pts = s.trace["trace"]
        x, y = arrays.proj.to_xy([p["lat"] for p in pts], [p["lon"] for p in pts])
        px[i], py[i] = x, y
        tm[i] = np.asarray([p["time"] for p in pts]) - pts[0]["time"]
    res = jax.jit(match_batch, static_argnums=(7,))(
        matcher._dg, matcher._du, jnp.asarray(px), jnp.asarray(py),
        jnp.asarray(tm), jnp.asarray(np.ones((B, T), bool)), matcher._params, 8,
    )
    edge = np.asarray(res.idx)
    cand_edge = np.asarray(res.cand.edge)
    sel = np.maximum(edge, 0)
    medge = cand_edge[np.arange(B)[:, None], np.arange(T)[None, :], sel]
    medge = np.where(edge >= 0, medge, -1)
    agr = float(np.mean([segment_agreement(arrays, medge[i], straces[i]) for i in range(B)]))
    # irregular real-style topology with oneways/ramps: still high agreement
    assert agr >= 0.85, agr


def test_cli_import(fixture_paths, tmp_path, capsys):
    out = tmp_path / "tiles"
    rc = osm.main([fixture_paths["xml"], "-o", str(out), "--json", str(tmp_path / "net.json")])
    assert rc == 0
    assert os.path.exists(str(out / "manifest.json"))
    assert os.path.exists(str(tmp_path / "net.json"))


def test_bbox_filter(fixture_paths):
    nodes, ways = osm.read_pbf(fixture_paths["pbf"])
    full = osm.network_from_osm(nodes, ways)
    # bbox covering only the south-west corner keeps fewer ways
    clipped = osm.network_from_osm(nodes, ways, bbox=(47.600, -122.34, 47.6065, -122.330))
    assert 0 < clipped.num_edges < full.num_edges
