"""The obs subsystem: registry semantics, Prometheus rendering, snapshot
merging, endpoint end-to-end, microbatcher histogram population, span
breakdowns, bounded instrumentation overhead, and the batch head's
cross-process snapshot merge."""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from reporter_tpu.obs import metrics as obs_metrics
from reporter_tpu.obs.metrics import Registry, merge
from reporter_tpu.obs.trace import Span


# -- registry semantics -----------------------------------------------------


def test_counter_concurrency_exact():
    reg = Registry()
    c = reg.counter("t_hits_total", "hits")
    n_threads, per_thread = 8, 5000

    def spin():
        for _ in range(per_thread):
            c.inc()

    ts = [threading.Thread(target=spin) for _ in range(n_threads)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    assert c.value == n_threads * per_thread


def test_label_semantics():
    reg = Registry()
    fam = reg.counter("t_req_total", "reqs", ("endpoint", "outcome"))
    a = fam.labels("report", "ok")
    assert fam.labels("report", "ok") is a  # same combination -> same child
    assert fam.labels(endpoint="report", outcome="ok") is a  # kwargs too
    b = fam.labels("report", "error")
    assert b is not a
    a.inc(2)
    b.inc()
    snap = reg.snapshot()["t_req_total"]
    assert snap["labelnames"] == ["endpoint", "outcome"]
    assert [["report", "error"], 1] in [[lv, v] for lv, v in snap["samples"]]
    with pytest.raises(ValueError):
        fam.labels("only-one")
    with pytest.raises(ValueError):
        fam.inc()  # labeled family has no default child
    with pytest.raises(ValueError):
        reg.gauge("t_req_total")  # kind conflict
    assert reg.counter("t_req_total", labelnames=("endpoint", "outcome")) is fam


def test_gauge_and_histogram_basics():
    reg = Registry()
    g = reg.gauge("t_depth", "queue depth")
    g.set(5)
    g.inc(2)
    g.dec()
    assert g.value == 6
    h = reg.histogram("t_lat_seconds", "latency", buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.5, 5.0):
        h.observe(v)
    s = reg.snapshot()["t_lat_seconds"]["samples"][0][1]
    assert s["counts"] == [1, 1, 1, 1] and s["count"] == 4
    assert s["sum"] == pytest.approx(5.555)
    with pytest.raises(ValueError):
        reg.histogram("t_bad", buckets=(1.0, 0.5))
    with pytest.raises(ValueError):
        reg.counter("bad name!")


def test_prometheus_render_golden():
    reg = Registry()
    c = reg.counter("t_req_total", "Requests served", ("route",))
    c.labels("a").inc(3)
    c.labels('q"uo\\te').inc()
    reg.gauge("t_depth", "Depth").set(2.5)
    h = reg.histogram("t_wait_seconds", "Wait", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(50.0)
    assert reg.render() == (
        '# HELP t_req_total Requests served\n'
        '# TYPE t_req_total counter\n'
        't_req_total{route="a"} 3\n'
        't_req_total{route="q\\"uo\\\\te"} 1\n'
        '# HELP t_depth Depth\n'
        '# TYPE t_depth gauge\n'
        't_depth 2.5\n'
        '# HELP t_wait_seconds Wait\n'
        '# TYPE t_wait_seconds histogram\n'
        't_wait_seconds_bucket{le="0.1"} 1\n'
        't_wait_seconds_bucket{le="1"} 2\n'
        't_wait_seconds_bucket{le="+Inf"} 3\n'
        't_wait_seconds_sum 50.55\n'
        't_wait_seconds_count 3\n'
    )


def test_snapshot_merge():
    rega, regb = Registry(), Registry()
    for reg, n in ((rega, 2), (regb, 3)):
        reg.counter("t_total", "", ("who",)).labels("x").inc(n)
        reg.gauge("t_inflight").set(n)
        h = reg.histogram("t_lat", buckets=(1.0, 10.0))
        h.observe(0.5)
        h.observe(float(n * 10))
    regb.counter("t_total", "", ("who",)).labels("y").inc(7)
    merged = merge(rega.snapshot(), regb.snapshot())
    samples = {tuple(lv): v for lv, v in merged["t_total"]["samples"]}
    assert samples[("x",)] == 5 and samples[("y",)] == 7
    assert merged["t_inflight"]["samples"][0][1] == 5  # gauges sum
    hist = merged["t_lat"]["samples"][0][1]
    assert hist["count"] == 4 and hist["counts"] == [2, 0, 2]
    # merge is json-safe round-trip
    assert merge(json.loads(json.dumps(rega.snapshot()))) == merge(rega.snapshot())


def test_collect_callback_runs_on_read():
    reg = Registry()
    g = reg.gauge("t_live")
    state = {"v": 0}
    reg.register_collect(lambda: g.set(state["v"]))
    state["v"] = 42
    assert reg.snapshot()["t_live"]["samples"][0][1] == 42
    state["v"] = 7
    assert "t_live 7" in reg.render()


# -- microbatcher instrumentation ------------------------------------------


class _StubMatcher:
    """match_many_async-compatible stub: instant device fn."""

    backend = "cpu"

    def match_many_async(self, traces):
        results = [{"segments": []} for _ in traces]
        return lambda: results


def _snap_hist(name):
    fam = obs_metrics.REGISTRY.snapshot().get(name)
    return fam["samples"][0][1]["count"] if fam else 0


def test_microbatcher_populates_histograms():
    from reporter_tpu.serve.service import MicroBatcher

    before = {n: _snap_hist(n) for n in (
        "reporter_microbatch_queue_wait_seconds",
        "reporter_microbatch_batch_fill",
        "reporter_microbatch_device_step_seconds",
    )}
    mb = MicroBatcher(_StubMatcher(), max_batch=8, max_wait_ms=1.0)
    out = mb.match_many([{"uuid": "u%d" % i, "trace": []} for i in range(20)])
    assert len(out) == 20
    # the finisher observes device-step after resolving futures; allow a tick
    deadline = time.monotonic() + 5.0
    while (_snap_hist("reporter_microbatch_device_step_seconds")
           <= before["reporter_microbatch_device_step_seconds"]):
        assert time.monotonic() < deadline, "device-step histogram never populated"
        time.sleep(0.01)
    after_wait = _snap_hist("reporter_microbatch_queue_wait_seconds")
    assert after_wait >= before["reporter_microbatch_queue_wait_seconds"] + 20
    assert (_snap_hist("reporter_microbatch_batch_fill")
            > before["reporter_microbatch_batch_fill"])


def test_microbatcher_clamps_nonpositive_inflight():
    from reporter_tpu.serve.service import MicroBatcher

    # maxsize<=0 would make the hand-off queue UNBOUNDED (ADVICE r05)
    assert MicroBatcher(_StubMatcher(), max_inflight=0)._finish_q.maxsize == 1
    assert MicroBatcher(_StubMatcher(), max_inflight=-3)._finish_q.maxsize == 1
    assert MicroBatcher(_StubMatcher(), max_inflight=5)._finish_q.maxsize == 5


def test_span_rides_through_batcher():
    from reporter_tpu.serve.service import MicroBatcher

    mb = MicroBatcher(_StubMatcher(), max_wait_ms=1.0)
    span = Span("report")
    mb.match({"uuid": "u", "trace": []}, span=span)
    span.finish()
    out = span.breakdown()
    assert out["span_id"] and out["batch_size"] >= 1
    assert {"queue_wait_s", "device_step_s", "total_s"} <= set(out["timings"])


def test_microbatcher_overhead():
    """Instrumentation must stay within 10% of the uninstrumented path over
    >= 1k requests against a stub device fn (plus a small absolute epsilon
    for scheduler jitter on loaded CI hosts)."""
    from reporter_tpu.serve.service import MicroBatcher

    n = 1000
    traces = [{"uuid": "u%d" % i, "trace": []} for i in range(n)]

    def wall(instrument: bool) -> float:
        mb = MicroBatcher(_StubMatcher(), max_batch=64, max_wait_ms=0.0,
                          instrument=instrument)
        t0 = time.perf_counter()
        mb.match_many(traces)
        return time.perf_counter() - t0

    # alternate and take the best of several runs so a one-off scheduler
    # stall can't decide the verdict in either direction; the absolute
    # epsilon absorbs the single-CPU scheduler jitter a full-suite run
    # layers on top of the 10% relative bound (PR 18 deflake)
    t_plain = min(wall(False) for _ in range(5))
    t_instr = min(wall(True) for _ in range(5))
    assert t_instr <= 1.10 * t_plain + 0.075, (t_instr, t_plain)


# -- service endpoints end-to-end ------------------------------------------


@pytest.fixture(scope="module")
def obs_service_url():
    from reporter_tpu.matching import MatcherConfig, SegmentMatcher
    from reporter_tpu.serve import ReporterService
    from reporter_tpu.tiles.arrays import build_graph_arrays
    from reporter_tpu.tiles.network import grid_city
    from reporter_tpu.tiles.ubodt import build_ubodt

    city = grid_city(rows=5, cols=5, spacing_m=150.0)
    arrays = build_graph_arrays(city, cell_size=100.0)
    ubodt = build_ubodt(arrays, delta=2000.0)
    matcher = SegmentMatcher(arrays=arrays, ubodt=ubodt, config=MatcherConfig())
    service = ReporterService(matcher, max_wait_ms=5.0)
    httpd = service.make_server("127.0.0.1", 0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    yield "http://127.0.0.1:%d" % httpd.server_port, arrays
    httpd.shutdown()


def _street_trace(arrays, n=10):
    nodes = [2 * 5 + c for c in range(5)]
    t = np.linspace(0.05, 0.9, n)
    xs = np.interp(t, np.linspace(0, 1, 5), arrays.node_x[nodes])
    ys = np.interp(t, np.linspace(0, 1, 5), arrays.node_y[nodes])
    lat, lon = arrays.proj.to_latlon(xs, ys)
    return {
        "uuid": "veh-obs",
        "trace": [{"lat": float(a), "lon": float(o), "time": 1000 + 15 * i}
                  for i, (a, o) in enumerate(zip(lat, lon))],
        "match_options": {"mode": "auto", "report_levels": [0, 1],
                          "transition_levels": [0, 1]},
    }


def _post(url, payload):
    req = urllib.request.Request(url, data=json.dumps(payload).encode(),
                                 headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=60) as r:
        return r.status, json.loads(r.read().decode())


_PROM_LINE = (
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[a-zA-Z_][a-zA-Z0-9_]*="(\\.|[^"\\])*"'
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="(\\.|[^"\\])*")*\})? -?[0-9.e+-]+(\n|$)')


def test_metrics_endpoint_exposition(obs_service_url):
    import re

    url, arrays = obs_service_url
    code, _ = _post(url + "/report", _street_trace(arrays))
    assert code == 200
    with urllib.request.urlopen(url + "/metrics", timeout=30) as r:
        assert r.status == 200
        assert r.headers["Content-Type"].startswith("text/plain")
        text = r.read().decode()
    # the acceptance set: every operating signal a batched service needs
    assert "reporter_microbatch_queue_wait_seconds_bucket{" in text
    assert "reporter_microbatch_device_step_seconds_bucket{" in text
    assert "reporter_microbatch_batch_fill_bucket{" in text
    assert 'reporter_compile_total{shape="' in text
    assert 'reporter_requests_total{endpoint="report",outcome="ok"}' in text
    # every non-comment line is valid exposition syntax
    for line in text.strip().splitlines():
        if line.startswith("#"):
            assert re.match(r"^# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]*", line), line
        else:
            assert re.match(_PROM_LINE, line), line
    # histogram invariants on a served family: cumulative and capped by count
    cum = [int(m.group(1)) for m in re.finditer(
        r'reporter_microbatch_batch_fill_bucket\{le="[^"]*"\} (\d+)', text)]
    assert cum == sorted(cum) and cum[-1] == int(re.search(
        r"reporter_microbatch_batch_fill_count (\d+)", text).group(1))


def test_statusz_snapshot(obs_service_url):
    url, _arrays = obs_service_url
    with urllib.request.urlopen(url + "/statusz", timeout=30) as r:
        out = json.loads(r.read().decode())
    assert out["uptime_s"] >= 0 and out["backend"] == "jax"
    assert out["latency_buckets_s"] == list(obs_metrics.LATENCY_BUCKETS_S)
    assert "max_batch" in out["batch"]
    assert "reporter_requests_total" in out["metrics"]
    assert out["metrics"]["reporter_requests_total"]["type"] == "counter"


def test_report_debug_breakdown(obs_service_url):
    url, arrays = obs_service_url
    code, out = _post(url + "/report?debug=1", _street_trace(arrays))
    assert code == 200
    dbg = out["debug"]
    assert len(dbg["span_id"]) == 16 and dbg["batch_size"] >= 1
    t = dbg["timings"]
    assert {"queue_wait_s", "device_step_s", "report_fn_s", "total_s"} <= set(t)
    assert t["total_s"] >= t["device_step_s"] >= 0
    # without the opt-in, no debug payload rides along
    code, out = _post(url + "/report", _street_trace(arrays))
    assert code == 200 and "debug" not in out


def test_profile_endpoint(obs_service_url):
    import os

    url, _arrays = obs_service_url
    with urllib.request.urlopen(url + "/debug/profile?seconds=0.05", timeout=60) as r:
        out = json.loads(r.read().decode())
    assert r.status == 200
    assert os.path.isdir(out["trace_dir"])
    # the capture actually wrote a trace artifact under the dir
    found = [f for _r, _d, fs in os.walk(out["trace_dir"]) for f in fs]
    assert found, "profiler capture produced no files"


# -- cross-process snapshot merge (batch pipeline) --------------------------


def test_batch_worker_snapshots_merge(tmp_path):
    from reporter_tpu.batch import pipeline

    arch = tmp_path / "arch"
    arch.mkdir()
    for i in range(2):
        with open(str(arch / ("day%d.csv" % i)), "w") as f:
            for j in range(3):
                f.write("veh-%d-%d,%d,37.75,-122.45,5\n" % (i, j, 1000 + j))

    pipeline.WORKER_SNAPSHOTS.clear()
    out = pipeline.get_traces(
        str(arch),
        valuer='lambda l: tuple(l.split(","))',
        time_pattern=None,
        concurrency=2,
        dest_dir=str(tmp_path / "traces"),
    )
    assert len(pipeline.WORKER_SNAPSHOTS) == 2, "one snapshot per spawn worker"
    merged = merge(*pipeline.WORKER_SNAPSHOTS)
    files = {tuple(lv): v for lv, v in
             merged["reporter_batch_source_files_total"]["samples"]}
    assert files[("ok",)] == 2  # one archive file per worker, summed
    points = merged["reporter_batch_points_gathered_total"]["samples"][0][1]
    assert points == 6
    assert len(list((tmp_path / "traces").iterdir())) >= 1
    assert out == str(tmp_path / "traces")


def _sample(snap, family, labels=()):
    fam = snap.get(family)
    if not fam:
        return 0
    for lv, v in fam["samples"]:
        if tuple(lv) == tuple(labels):
            return v
    return 0


def test_batch_head_metrics_flag(tmp_path, capsys):
    """python -m reporter_tpu.batch --metrics prints ONE merged JSON
    snapshot covering the head and every fan-out worker process."""
    from reporter_tpu.batch import pipeline
    from reporter_tpu.batch.__main__ import main as batch_main

    # the head registry is process-wide and other tests feed it: assert on
    # deltas, and drop worker snapshots collected by earlier tests
    pipeline.WORKER_SNAPSHOTS.clear()
    before = obs_metrics.REGISTRY.snapshot()

    conf = tmp_path / "conf.json"
    conf.write_text(json.dumps({
        "network": {"type": "grid", "rows": 3, "cols": 3, "spacing_m": 150.0},
        "backend": "cpu",
    }))
    arch = tmp_path / "arch"
    arch.mkdir()
    # two short same-vehicle drives near the grid origin (cpu oracle backend:
    # no device, fast) split over two archive files for the phase-1 fan-out
    for i in range(2):
        with open(str(arch / ("part%d.csv" % i)), "w") as f:
            for j in range(4):
                f.write("veh-%d,%d,%.6f,%.6f,5\n"
                        % (i, 1000 + 15 * j, 37.7502, -122.4498 + 0.0002 * j))
    rc = batch_main([
        "--src", str(arch),
        "--match-config", str(conf),
        "--src-time-pattern", "",
        "--src-valuer", 'lambda l: tuple(l.split(","))',
        "--dest", "dir:" + str(tmp_path / "out"),
        "--concurrency", "2",
        "--privacy", "1",
        "--metrics",
    ])
    assert rc == 0
    last = capsys.readouterr().out.strip().splitlines()[-1]
    snap = json.loads(last)
    # both workers' counts merged into one dump (delta over the head's
    # pre-run registry: this run added 2 ok files / 8 points, all of them
    # counted in worker processes)
    assert (_sample(snap, "reporter_batch_source_files_total", ("ok",))
            == _sample(before, "reporter_batch_source_files_total", ("ok",)) + 2)
    assert (_sample(snap, "reporter_batch_points_gathered_total")
            == _sample(before, "reporter_batch_points_gathered_total") + 8)
    # phase 2 ran in the head process; its counters ride the same snapshot
    assert "reporter_batch_windows_matched_total" in snap
