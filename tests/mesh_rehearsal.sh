#!/usr/bin/env bash
# Mesh gating rehearsal (the CI `mesh-rehearsal` leg; runnable locally):
# ONE replica is forced onto an 8-virtual-device dp mesh
# (XLA_FLAGS=--xla_force_host_platform_device_count=8 plus the
# REPORTER_DEVICES override — a stock config, the topology arrives by
# env exactly as a pod supervisor would deliver it) and serves the
# per-point streaming scenario with the FULL serving composition ON:
# the device-resident session arena, the tiered UBODT hot-bucket
# arena, and the sparse-gap matching model — the whole program family
# of docs/performance.md "One logical matcher per pod" dispatching
# through the partition-rule table at once.  The verdict:
#
#   1. loadgen streaming SLO verdict green (rc 0): the mesh-sharded
#      replica serves real per-point traffic inside its objectives
#   2. the topology is really advertised: /health capacity.devices == 8
#      with mesh {dp: 8, gp: 1}, admission caps scaled 8x over the
#      per-chip config, and the ROUTER's /statusz fleet row carries
#      devices == 8 — the weighted ranking consumed the capacity block
#   3. the arena is really SHARDED across the mesh: /statusz
#      session_arena shows devices == 8, hot_slots a multiple of 8, and
#      the per-chip views exactly 1/8 of the pod totals; ubodt_tier is
#      live (tiering composes with the mesh instead of disabling)
#   4. readbacks stay FLAT through a steady mid-stream window: the
#      dp-sharded slab still performs zero per-step host readbacks —
#      sharding the slot axis did not sneak a host gather into the
#      donated in-place session step
#
# Usage: tests/mesh_rehearsal.sh [workdir]
set -euo pipefail

. "$(dirname "$0")/rehearsal_lib.sh"
export REPORTER_RETRY_BASE_S="${REPORTER_RETRY_BASE_S:-0.05}"
export REPORTER_ROUTER_PROBE_S="${REPORTER_ROUTER_PROBE_S:-0.25}"
# the mesh under test: 8 virtual CPU devices, the replica spans all of
# them as a dp-8 mesh (docs/serving-fleet.md Knobs)
export XLA_FLAGS="--xla_force_host_platform_device_count=8${XLA_FLAGS:+ $XLA_FLAGS}"
export REPORTER_DEVICES=8
# the full serving composition, pinned explicitly so this gate keeps
# meaning it even if a serving default moves
export REPORTER_SESSION_ARENA=1
export REPORTER_SPARSE=1
export REPORTER_UBODT_HOT_BYTES="${REPORTER_UBODT_HOT_BYTES:-16384}"
# serving objectives (loose: 8 virtual devices SHARE the runner's host
# cores, so per-dispatch latency is the oversubscription's, not the
# mesh's — correctness of the sharded data plane is the gate)
export REPORTER_SLO_AVAILABILITY=0.95
export REPORTER_SLO_P99_MS=12000
export REPORTER_SLO_P999_MS=0
export REPORTER_SLO_DEGRADED_FRAC=0
export REPORTER_SLO_STREAM_P99_MS=4000
reh_init "${1:-}" reporter-mesh
export REPORTER_XLA_CACHE_DIR="$WORK/xla-cache"
ROUTER_PORT=18281
BASE_PORT=18282
echo "mesh rehearsal workdir: $WORK (dp-8 replica, arena+tiering+sparse ON)"

cat > "$WORK/config.json" <<EOF
{
  "network": {"type": "grid", "rows": 8, "cols": 8, "spacing_m": 200},
  "matcher": {"sigma_z": 4.07, "beta": 3.0, "search_radius": 50.0,
              "length_buckets": [16],
              "session_buckets": [4, 16],
              "session_tail_points": 64,
              "warmup_batch_sizes": [1, 4, 16]},
  "backend": "jax",
  "batch": {"max_batch": 64, "max_wait_ms": 5, "session_wait_ms": 2}
}
EOF

# ---- boot the one-replica, eight-chip fleet -------------------------------
python tools/fleet.py --config "$WORK/config.json" --replicas 1 \
    --base-port "$BASE_PORT" --router-port "$ROUTER_PORT" \
    --workdir "$WORK" --warmup --cpu-default --drain-grace 20 \
    > "$WORK/fleet.log" 2>&1 &
FLEET_PID=$!
reh_track_fleet "$FLEET_PID" "$WORK"

if ! reh_wait_fleet "http://127.0.0.1:$ROUTER_PORT" 1 "$BASE_PORT" 1 600 warmed; then
    echo "FAIL: the mesh replica never warmed; fleet log tail:"
    tail -30 "$WORK/fleet.log"
    for f in "$WORK"/replica-*.log "$WORK"/router.log; do
        echo "--- $f"; tail -10 "$f" 2>/dev/null || true
    done
    exit 1
fi
echo "fleet up: 1 warmed replica spanning 8 virtual devices"

# 2 + 3. the advertised topology and the sharded planes, BEFORE load
python - "$BASE_PORT" "http://127.0.0.1:$ROUTER_PORT" <<'EOF'
import json, sys, urllib.request

base, router = int(sys.argv[1]), sys.argv[2]

def get(url):
    with urllib.request.urlopen(url, timeout=15) as f:
        return json.loads(f.read().decode())

h = get("http://127.0.0.1:%d/health" % base)
cap = h.get("capacity")
assert cap, "no capacity block on /health: %r" % h
assert cap["devices"] == 8, cap
assert cap.get("mesh") == {"dp": 8, "gp": 1}, cap
assert cap["max_device_batch"] % 8 == 0 and cap["max_device_batch"] >= 8, cap
print("capacity advertised: devices=8 mesh=%r max_device_batch=%d "
      "max_device_points=%d" % (cap["mesh"], cap["max_device_batch"],
                                cap["max_device_points"]))

sz = get("http://127.0.0.1:%d/statusz" % base)
a = sz.get("session_arena")
assert a is not None, "replica serves without a session arena"
assert a["devices"] == 8, a
assert a["hot_slots"] % 8 == 0, a
assert a["hot_slots_per_chip"] * 8 == a["hot_slots"], a
assert a["hot_bytes_per_chip"] * 8 == a["hot_bytes"], a
print("session arena sharded: %d slots over 8 chips (%d/chip, %dB/chip)"
      % (a["hot_slots"], a["hot_slots_per_chip"], a["hot_bytes_per_chip"]))

tier = sz.get("ubodt_tier")
assert tier is not None, "tiering disabled itself under the mesh"
assert tier["hot_bytes"] > 0 and tier["hot_rows"] > 0, tier
print("ubodt tiering live under the mesh: hot_bytes=%d hot_rows=%d"
      % (tier["hot_bytes"], tier["hot_rows"]))

sp = sz.get("sparse")
assert sp and sp.get("enabled"), "sparse model not enabled: %r" % sp
print("sparse-gap model enabled")

fleet = get(router + "/statusz")
row = fleet["fleet"][0]
assert row.get("devices") == 8, (
    "router never learned the replica's mesh size: %r" % row)
print("router fleet row advertises devices=8 (capacity-weighted ranking fed)")
EOF

# ---- the loadgen stream scenario against the mesh replica ------------------
python tools/loadgen.py --url "http://127.0.0.1:$ROUTER_PORT" \
    --stream \
    --rate 15 --duration 25 --vehicles 16 --points 48 --window 16 --grid 8 \
    --seed 13 --concurrency 24 --timeout-s 10 \
    --slo-availability 0.95 --slo-p99-ms 12000 \
    --out "$WORK/loadgen_stream.json" &
LOADGEN_PID=$!

# 4. steady-state readback window: two mid-stream scrapes of the arena's
# readback counter must not move (zero per-step host transfers even with
# the slab dp-sharded over 8 devices)
_scrape_readbacks() {
    python - "$BASE_PORT" <<'EOF'
import sys, urllib.request

sys.path.insert(0, ".")
from reporter_tpu.obs.quantile import parse_metrics

base = int(sys.argv[1])
with urllib.request.urlopen(
        "http://127.0.0.1:%d/metrics" % base, timeout=15) as f:
    m = parse_metrics(f.read().decode())
tot = 0
for _lv, v in m.get("reporter_session_arena_readbacks_total", {}).items():
    tot += int(v)
print(tot)
EOF
}
sleep 5
RB_0=$(_scrape_readbacks)
sleep 5
RB_1=$(_scrape_readbacks)
if [ "$RB_0" != "$RB_1" ]; then
    echo "FAIL: arena readbacks grew $RB_0 -> $RB_1 during steady-state"
    echo "      streaming on the dp-8 mesh — sharding the slab leaked a"
    echo "      per-step host transfer"
    exit 1
fi
echo "steady-state readbacks flat on the mesh: $RB_0 across both scrapes"

set +e
wait "$LOADGEN_PID"
LOADGEN_RC=$?
set -e
if [ "$LOADGEN_RC" != 0 ]; then
    echo "FAIL: loadgen rc $LOADGEN_RC — the streaming SLO did not hold on"
    echo "      the mesh replica (artifact: loadgen_stream.json)"
    python -c "
import json; a = json.load(open('$WORK/loadgen_stream.json'))
print(json.dumps({k: a.get(k) for k in ('status', 'quantiles', 'slo')}, indent=1))" \
        2>/dev/null || true
    tail -20 "$WORK/router.log"
    exit 1
fi
echo "loadgen streaming SLO verdict: PASS (rc 0) against the dp-8 replica"

# resident sessions actually landed in the sharded slab under load
python - "$BASE_PORT" <<'EOF'
import json, sys, urllib.request

base = int(sys.argv[1])
with urllib.request.urlopen(
        "http://127.0.0.1:%d/statusz" % base, timeout=15) as f:
    sz = json.loads(f.read().decode())
a = sz["session_arena"]
assert a["hot_used"] > 0, (
    "no session ever went device-resident on the mesh: %r" % a)
print("mesh slab occupancy after load: %d/%d hot slots used, "
      "%d promotions" % (a["hot_used"], a["hot_slots"], a["promotions"]))
EOF

reh_stop_fleet
echo "mesh rehearsal: PASS"
