"""Realistic-city OSM extract generator (synth/osm_city.py).

The bench's "real map" substitute (no egress): must be deterministic, must
round-trip the actual PBF ingestion path, and must show the structural
properties that distinguish it from the uniform grid — road-class mix,
one-ways, internal ramps, curved multi-segment edges, and a river that
forces route distances far above straight-line distance.
"""

import numpy as np
import pytest

from reporter_tpu.synth.osm_city import realistic_city, realistic_city_network
from reporter_tpu.tiles.arrays import build_graph_arrays
from reporter_tpu.tiles.ubodt import build_ubodt

ROWS = COLS = 24


@pytest.fixture(scope="module")
def net():
    return realistic_city_network(ROWS, COLS, seed=3)


@pytest.fixture(scope="module")
def arrays(net):
    return build_graph_arrays(net, cell_size=100.0)


def test_deterministic():
    n1, w1 = realistic_city(10, 10, seed=5)
    n2, w2 = realistic_city(10, 10, seed=5)
    assert n1 == n2
    assert [(w.id, w.refs, w.tags) for w in w1] == [(w.id, w.refs, w.tags) for w in w2]


def test_structural_mix(arrays):
    levels = np.bincount(arrays.edge_level, minlength=3)
    assert levels[0] > 0 and levels[1] > 0 and levels[2] > 0
    assert levels[2] > levels[1] > 0  # locals dominate
    assert arrays.edge_internal.sum() >= 8  # motorway_link ramps
    # one-ways: some directed edges without a reverse twin
    pairs = set(zip(arrays.edge_from.tolist(), arrays.edge_to.tolist()))
    assert sum(1 for a, b in pairs if (b, a) not in pairs) > 50
    # curved streets: some edges carry more than one shape segment
    seg_per_edge = np.bincount(arrays.shp_edge, minlength=arrays.num_edges)
    assert (seg_per_edge > 1).sum() > 20
    # speed diversity
    assert len(np.unique(arrays.edge_speed)) >= 4


def test_river_forces_detours(net, arrays):
    """Straight-line neighbours across the river must route the long way
    round (or not at all within delta) — the regime where the HMM's
    |route - gc| transition discriminates."""
    ubodt = build_ubodt(arrays, delta=4000.0)
    node_y = arrays.node_y
    node_x = arrays.node_x
    # node pairs straddling the river mid-band, horizontally close
    mid = node_y.min() + (node_y.max() - node_y.min()) * 0.52
    detours = 0
    checked = 0
    for i in range(arrays.num_nodes):
        if not (mid - 380 < node_y[i] < mid - 120):
            continue
        for j in range(arrays.num_nodes):
            if not (mid + 120 < node_y[j] < mid + 380):
                continue
            if abs(node_x[i] - node_x[j]) > 250:
                continue
            gc = float(np.hypot(node_x[i] - node_x[j], node_y[i] - node_y[j]))
            d, _, _ = ubodt.lookup_full(i, j)
            checked += 1
            if d > 2.0 * gc:  # unreachable (inf) also counts as a detour
                detours += 1
    assert checked >= 5, "no river-straddling pairs sampled"
    assert detours / checked > 0.5, (detours, checked)


def test_largest_component_dominates(net, arrays):
    """Dead-end pruning and the river must not shatter the graph: the bulk
    of nodes stay mutually routable (traces synthesized on the city need
    somewhere to drive)."""
    n = arrays.num_nodes
    seen = np.zeros(n, bool)
    comp_best = 0
    for s in range(n):
        if seen[s]:
            continue
        stack = [s]
        seen[s] = True
        size = 0
        while stack:
            u = stack.pop()
            size += 1
            for k in range(arrays.out_start[u], arrays.out_start[u + 1]):
                v = int(arrays.edge_to[arrays.out_edges[k]])
                if not seen[v]:
                    seen[v] = True
                    stack.append(v)
        comp_best = max(comp_best, size)
    assert comp_best > 0.85 * n, (comp_best, n)
