"""Streaming stack: formatter DSL, serdes, windowing, anonymiser, and the
end-to-end pipeline against the in-process TPU matcher."""

import glob
import os

import numpy as np
import pytest

from reporter_tpu.stream.anonymiser import AnonymisingProcessor, cull, quantised_tiles
from reporter_tpu.stream.batch import Batch, equirectangular_m
from reporter_tpu.stream.batcher import BatchingProcessor
from reporter_tpu.stream.client import LocalMatcherClient
from reporter_tpu.stream.formatter import Formatter, joda_to_strptime
from reporter_tpu.stream.point import Point
from reporter_tpu.stream.segment import (
    INVALID_SEGMENT_ID,
    Segment,
    pack_list,
    unpack_list,
)
from reporter_tpu.stream.topology import StreamPipeline, build_pipeline


# -- Point ---------------------------------------------------------------


def test_point_serde_roundtrip():
    p = Point(3.465725, -76.5135033, 51, 1495037969)
    data = p.pack()
    assert len(data) == 20
    q = Point.unpack(data)
    assert q.accuracy == 51 and q.time == 1495037969
    assert q.lat == pytest.approx(3.465725, abs=1e-5)
    assert q.lon == pytest.approx(-76.5135033, abs=1e-4)


def test_point_json():
    assert Point(0.0, 0.0, 7, 10).to_json() == '{"lat":0,"lon":0,"time":10,"accuracy":7}'
    assert (
        Point(1.5, -2.25, 3, 4).to_json()
        == '{"lat":1.5,"lon":-2.25,"time":4,"accuracy":3}'
    )


# -- Formatter (reference FormatterTest.java parity) ----------------------


def test_formatter_sv():
    f = Formatter.from_config(",sv,\\|,1,9,10,0,5,yyyy-MM-dd HH:mm:ss")
    uuid, p = f.format("2017-01-01 06:05:40|w00t||||6.5||||0.0|0.0")
    assert uuid == "w00t"
    assert p.accuracy == 7  # 6.5 ceiled
    assert p.time == 1483250740
    assert p.lat == 0.0 and p.lon == 0.0


def test_formatter_json():
    f = Formatter.from_config("@json@id@la@lo@t@a@yyyy-MM-dd HH:mm:ss")
    uuid, p = f.format(
        '{"t":"2017-01-01 06:05:40","id":"w00t","la":0.0,"lo":0.0,"a":6.5}'
    )
    assert uuid == "w00t" and p.accuracy == 7 and p.time == 1483250740


def test_formatter_json_epoch():
    f = Formatter.from_config("@json@id@latitude@longitude@timestamp@accuracy")
    uuid, p = f.format(
        '{"timestamp":1495037969,"id":"abc","accuracy":51.305,'
        '"latitude":3.465725,"longitude":-76.5135033}'
    )
    assert uuid == "abc" and p.accuracy == 52 and p.time == 1495037969


def test_formatter_bogus():
    for bogus in ("%sv%,%a", "%json%a%b%c%d", "bogus_formatter"):
        with pytest.raises(Exception):
            Formatter.from_config(bogus)


def test_formatter_line_garbage_fails_cleanly():
    """Line-level garbage (the constant diet of a production feed: wrong
    column counts, non-numeric fields, NULs, huge lines, truncated
    multibyte text) must either parse to a (uuid, Point) or raise an
    ordinary exception for the pipeline's swallow-and-log seam -- never
    hang or take the process down."""
    import numpy as np

    f = Formatter.from_config(",sv,\\|,0,2,3,1,4")
    rng = np.random.default_rng(8)
    lines = [
        "", "|", "||||", "a|b|c|d|e", "veh|notatime|1.0|2.0|5",
        "veh|100|91.0|181.0|5", "veh|100|nan|inf|5",
        "veh|100|1.0|2.0|" + "9" * 400, "\x00\x00|\x00|\x00|\x00|\x00",
        "veh|100|1.0|2.0|5|extra|columns|everywhere",
        "x" * 100000,
    ]
    for _ in range(30):
        n = int(rng.integers(0, 12))
        lines.append("|".join(
            "".join(chr(int(c)) for c in rng.integers(32, 127, rng.integers(0, 9)))
            for _ in range(n)))
    ok = 0
    for line in lines:
        try:
            out = f.format(line)
            if out is not None:
                ok += 1
        except Exception as e:  # noqa: BLE001 - clean failure is the contract
            assert not isinstance(
                e, (SystemExit, KeyboardInterrupt, MemoryError))
    # sanity: a well-formed line still parses
    uuid, p = f.format("veh|100|37.75|-122.45|5")
    assert uuid == "veh" and p.time == 100


def test_joda_conversion():
    assert joda_to_strptime("yyyy-MM-dd HH:mm:ss") == "%Y-%m-%d %H:%M:%S"
    with pytest.raises(ValueError):
        joda_to_strptime("QQQ")


# -- Segment --------------------------------------------------------------


def test_segment_serde_and_csv():
    s = Segment(id=0b1010_001, next_id=None, min=100.2, max=163.7, length=120, queue=5)
    data = s.pack()
    assert len(data) == 40
    t = Segment.unpack(data)
    assert t.id == s.id and t.next_id == INVALID_SEGMENT_ID
    assert t.min == pytest.approx(100.2) and t.max == pytest.approx(163.7)
    # csv: duration rounded, min floored / max ceiled, empty next_id
    row = t.csv_row("AUTO", "SRC")
    assert row == "81,,63,1,120,5,100,164,SRC,AUTO"
    lst = unpack_list(pack_list([s, t]))
    assert len(lst) == 2 and lst[1].length == 120


def test_segment_validity_and_tile():
    good = Segment(id=(42 << 25) | (7 << 3) | 1, next_id=3, min=1.0, max=2.0, length=5, queue=0)
    assert good.valid()
    assert good.tile_id() == (7 << 3) | 1
    assert not Segment(id=1, next_id=None, min=0, max=2.0, length=5, queue=0).valid()
    assert not Segment(id=1, next_id=None, min=3.0, max=2.0, length=5, queue=0).valid()
    assert not Segment(id=1, next_id=None, min=1.0, max=2.0, length=0, queue=0).valid()
    assert not Segment(id=1, next_id=None, min=1.0, max=2.0, length=5, queue=-1).valid()


# -- Batch ----------------------------------------------------------------


def _pt(lat, lon, t):
    return Point(lat, lon, 5, t)


def test_batch_separation_and_gate():
    b = Batch(_pt(0.0, 0.0, 0))
    b.update(_pt(0.0, 0.005, 30))  # ~557 m east at the equator
    assert b.max_separation == pytest.approx(556.6, rel=0.01)
    assert not b.meets(500, 10, 60)  # too few points, too little time
    for i in range(2, 11):
        b.update(_pt(0.0, 0.005, i * 30))
    assert b.meets(500, 10, 60)


def test_batch_serde_roundtrip():
    b = Batch(_pt(1.0, 2.0, 3))
    b.update(_pt(1.1, 2.1, 4))
    b.last_update = 99
    c = Batch.unpack(b.pack())
    assert len(c.points) == 2 and c.last_update == 99
    assert c.max_separation == pytest.approx(b.max_separation)


def test_serde_corruption_fails_cleanly():
    """Truncated / bit-flipped wire buffers (what a half-written Kafka
    message or a bad checkpoint produces) must raise ordinary exceptions
    or decode to garbage values -- never hang, exit, or blow memory.
    Mirrors the binary formats' role at the reference's processor
    boundaries (Point 20 B, Segment 40 B, Batch list serde)."""
    import numpy as np

    from reporter_tpu.stream.point import Point
    from reporter_tpu.stream.segment import Segment

    b = Batch(_pt(1.0, 2.0, 3))
    for i in range(2, 8):
        b.update(_pt(1.0 + 0.01 * i, 2.0, i * 15))
    seg = Segment(id=123456, next_id=789, min=100.0, max=160.0,
                  length=250, queue=0)
    blobs = [b.pack(), seg.pack(), _pt(3.3, 4.4, 5).pack()]
    rng = np.random.default_rng(1)
    unpackers = [Batch.unpack, Segment.unpack, Point.unpack]
    for blob, unpack in zip(blobs, unpackers):
        cases = [blob[:k] for k in (0, 1, len(blob) // 2, len(blob) - 1)]
        for _ in range(12):
            bb = bytearray(blob)
            bb[int(rng.integers(0, len(blob)))] ^= 0xFF
            cases.append(bytes(bb))
        for payload in cases:
            try:
                unpack(payload)
            except Exception as e:  # noqa: BLE001 - clean failure is a pass
                assert not isinstance(
                    e, (SystemExit, KeyboardInterrupt, MemoryError))


def test_batch_trim_on_shape_used():
    b = Batch(_pt(0.0, 0.0, 0))
    for i in range(1, 5):
        b.update(_pt(0.0, 0.001 * i, i))
    b.apply_response({"shape_used": 3})
    assert len(b.points) == 2
    assert b.points[0].time == 3
    # separation recomputed over the survivors
    assert b.max_separation == pytest.approx(
        equirectangular_m(b.points[1], b.points[0])
    )
    # unusable response clears everything
    b.apply_response(None)
    assert not b.points and b.max_separation == 0.0


# -- BatchingProcessor -----------------------------------------------------


class FakeClient:
    """Consumes every trace fully, reporting one fixed segment pair."""

    def __init__(self):
        self.requests = []

    def report_many(self, requests):
        self.requests.extend(requests)
        out = []
        for r in requests:
            n = len(r["trace"])
            out.append(
                {
                    "shape_used": n,
                    "datastore": {
                        "reports": [
                            {
                                "id": 8,
                                "next_id": 16,
                                "t0": r["trace"][0]["time"],
                                "t1": r["trace"][-1]["time"],
                                "length": 100,
                                "queue_length": 0,
                            }
                        ]
                    },
                }
            )
        return out

    def report_one(self, request):
        return self.report_many([request])[0]


def test_batcher_reports_and_trims():
    client = FakeClient()
    forwarded = []
    bp = BatchingProcessor(
        client, lambda k, s: forwarded.append((k, s)), report_dist=100,
        report_count=5, report_time=30, microbatch_size=1,
    )
    t0 = 1_483_250_000
    for i in range(5):
        bp.process("veh-1", _pt(0.0, 0.001 * i, t0 + i * 10), (t0 + i * 10) * 1000)
    # 5 points, 40s, ~445m -> gate passed at the 5th point, flushed, trimmed
    assert len(client.requests) == 1
    assert [k for k, _ in forwarded] == ["8 16"]
    assert forwarded[0][1].valid()
    assert "veh-1" not in bp.store  # fully consumed


def test_batcher_eviction_relaxed():
    client = FakeClient()
    forwarded = []
    bp = BatchingProcessor(client, lambda k, s: forwarded.append((k, s)))
    t0 = 1_483_250_000
    bp.process("veh-2", _pt(0.0, 0.0, t0), t0 * 1000)
    bp.process("veh-2", _pt(0.0, 0.0004, t0 + 5), (t0 + 5) * 1000)
    # nowhere near the normal gate; 2 points qualifies for the relaxed one
    bp.punctuate((t0 + 5) * 1000 + bp.session_gap_ms + 1)
    assert len(client.requests) == 1
    assert "veh-2" not in bp.store
    assert forwarded


def test_batcher_single_point_evicted_silently():
    client = FakeClient()
    bp = BatchingProcessor(client, lambda k, s: None)
    bp.process("veh-3", _pt(0.0, 0.0, 100), 100_000)
    bp.punctuate(100_000 + bp.session_gap_ms + 1)
    assert not client.requests and "veh-3" not in bp.store


def test_batcher_microbatch_pools():
    client = FakeClient()
    bp = BatchingProcessor(
        client, lambda k, s: None, report_dist=50, report_count=2, report_time=0,
        microbatch_size=8,
    )
    t0 = 1_483_250_000
    for v in range(3):
        bp.process("veh-%d" % v, _pt(0.0, 0.0, t0), t0 * 1000)
        bp.process("veh-%d" % v, _pt(0.0, 0.001, t0 + 10), (t0 + 10) * 1000)
    assert not client.requests  # pooled, not yet flushed
    bp.flush_ready()
    assert len(client.requests) == 3  # one micro-batch of three traces


# -- Anonymiser ------------------------------------------------------------


def _seg(sid, nid, t0, t1):
    return Segment(id=sid, next_id=nid, min=t0, max=t1, length=100, queue=0)


def test_quantised_tiles_span():
    s = _seg(8, 16, 3590.0, 3610.0)
    tiles = quantised_tiles(s, 3600)
    assert tiles == [(0, 8 & 0x1FFFFFF), (3600, 8 & 0x1FFFFFF)]


def test_cull_trailing_group():
    # the reference's in-place cull keeps a trailing under-count group that
    # follows a passing one (AnonymisingProcessor.java:155-175); ours must not
    rows = sorted(
        [_seg(1, 2, 10, 20), _seg(1, 2, 11, 21), _seg(3, 4, 12, 22)],
        key=Segment.sort_key,
    )
    kept = cull(rows, 2)
    assert len(kept) == 2 and all(s.id == 1 for s in kept)


def test_anonymiser_flush(tmp_path):
    out = str(tmp_path / "tiles")
    ap = AnonymisingProcessor(
        privacy=2, quantisation=3600, output=out, source="TEST", mode="auto"
    )
    for i in range(3):
        ap.process("8 16", _seg(8, 16, 7200 + i, 7230 + i))
    ap.process("24 -", _seg(24, None, 7200, 7230))  # lone observation: culled
    ap.punctuate()
    files = glob.glob(os.path.join(out, "*", "*", "*", "*"))
    assert len(files) == 1
    body = open(files[0]).read()
    lines = body.strip().split("\n")
    assert lines[0] == Segment.column_layout()
    assert len(lines) == 4  # header + 3 surviving observations
    assert all(line.split(",")[9] == "AUTO" for line in lines[1:])
    # path layout {start}_{end}/{level}/{index}/{source}.{uuid}
    rel = os.path.relpath(files[0], out).split(os.sep)
    assert rel[0] == "7200_10799"
    assert rel[1] == str(8 & 0x7) and rel[2] == str((8 >> 3) & 0x3FFFFF)
    assert rel[3].startswith("TEST.")


def test_anonymiser_slicing():
    ap = AnonymisingProcessor(
        privacy=1, quantisation=3600, output="unused", source="S",
        store=type("N", (), {"put": lambda self, k, b: None})(), slice_size=2,
    )
    for i in range(5):
        ap.process("k", _seg(8, 16, 100 + i, 110 + i))
    # 5 observations with slice_size 2 -> slices 0,1 full + slice 2 current
    assert ap.map[(0, 8 & 0x1FFFFFF)] == 2
    assert sum(len(v) for v in ap.slices.values()) == 5


def test_anonymiser_validation():
    with pytest.raises(ValueError):
        AnonymisingProcessor(privacy=0, quantisation=3600, output="x", source="s")
    with pytest.raises(ValueError):
        AnonymisingProcessor(privacy=1, quantisation=30, output="x", source="s")


# -- end to end: raw SV lines -> tiles ------------------------------------


@pytest.fixture(scope="module")
def grid_matcher():
    from reporter_tpu.matching import MatcherConfig, SegmentMatcher
    from reporter_tpu.tiles.network import grid_city

    cfg = MatcherConfig()
    city = grid_city(rows=5, cols=5, spacing_m=150.0)
    return SegmentMatcher(network=city, config=cfg, backend="jax")


def _grid_pipeline(grid_matcher, out):
    """Grid-scale pipeline used by the end-to-end and garbage tests: same
    options, same report-gate tuning for the 5x5 test grid."""
    client = LocalMatcherClient(grid_matcher, threshold_sec=15)
    pipeline = build_pipeline(
        format_config=",sv,\\|,0,1,2,3,4",
        client=client,
        privacy=1,
        quantisation=3600,
        output=out,
        source="CI",
        report_levels=(0, 1, 2),
        transition_levels=(0, 1, 2),
        microbatch_size=4,
    )
    pipeline.batcher.report_dist = 200
    pipeline.batcher.report_count = 8
    pipeline.batcher.report_time = 30
    return pipeline


def test_stream_end_to_end(grid_matcher, tmp_path):
    from reporter_tpu.synth.generator import TraceSynthesizer

    out = str(tmp_path / "results")
    pipeline = _grid_pipeline(grid_matcher, out)

    synth = TraceSynthesizer(grid_matcher.arrays, seed=7)
    for v in range(3):
        st = synth.synthesize(24, dt=15.0, sigma=3.0, uuid="veh-%d" % v)
        for pt in st.trace["trace"]:
            line = "veh-%d|%.7f|%.7f|%d|%d" % (
                v, pt["lat"], pt["lon"], int(pt["time"]), pt["accuracy"]
            )
            pipeline.feed(line, int(pt["time"] * 1000))
    pipeline.close()

    assert pipeline.formatted == 72 and pipeline.dropped == 0
    assert pipeline.batcher.reported_pairs > 0
    files = glob.glob(os.path.join(out, "*", "*", "*", "*"))
    assert files, "no tiles written"
    rows = 0
    for f in files:
        lines = open(f).read().strip().split("\n")
        assert lines[0] == Segment.column_layout()
        rows += len(lines) - 1
    assert rows >= pipeline.batcher.reported_pairs  # buckets may duplicate


def test_stream_swallows_garbage_records(grid_matcher, tmp_path):
    """The reference's swallow-and-log seam
    (KeyedFormattingProcessor.java:39-41): arbitrary junk interleaved with
    valid records must never sink the pipeline, and the valid records must
    still produce their tiles."""
    import random

    from reporter_tpu.synth.generator import TraceSynthesizer

    out = str(tmp_path / "results")
    pipeline = _grid_pipeline(grid_matcher, out)

    rng = random.Random(1234)
    alphabet = "abc|,;\x00\xff{}[]\"'\\0123456789.eE+- \t"
    def junk():
        return "".join(rng.choice(alphabet) for _ in range(rng.randrange(0, 60)))

    synth = TraceSynthesizer(grid_matcher.arrays, seed=7)
    n_junk = 0
    for v in range(2):
        st = synth.synthesize(24, dt=15.0, sigma=3.0, uuid="veh-%d" % v)
        for pt in st.trace["trace"]:
            for _ in range(2):  # junk before every valid record
                pipeline.feed(junk(), int(pt["time"] * 1000))
                n_junk += 1
            # near-miss junk: right separator count, broken fields
            pipeline.feed("veh-x|not-a-lat|1e999|%d|nan" % int(pt["time"]),
                          int(pt["time"] * 1000))
            n_junk += 1
            line = "veh-%d|%.7f|%.7f|%d|%d" % (
                v, pt["lat"], pt["lon"], int(pt["time"]), pt["accuracy"]
            )
            pipeline.feed(line, int(pt["time"] * 1000))
    pipeline.close()

    assert pipeline.formatted == 48  # every valid record still made it
    assert pipeline.dropped == n_junk  # every junk record swallowed
    files = glob.glob(os.path.join(out, "*", "*", "*", "*"))
    assert files, "garbage starved the pipeline of its valid tiles"


def test_cli_stdin_fallback_for_embedders(tmp_path, monkeypatch):
    """An embedder that replaced sys.stdin with a plain text object (no
    .buffer.raw) must still stream records through the CLI: the fallback
    line-iteration loop feeds every consumed line and flushes on EOF."""
    import io
    import sys

    from reporter_tpu.stream.__main__ import main

    lines = "".join(
        "1|u-%d|37.75|-122.45|5|%d\n" % (i % 2, 1000 + i * 5)
        for i in range(30)
    )
    monkeypatch.setattr(sys, "stdin", io.StringIO(lines))
    out_dir = tmp_path / "tiles"
    rc = main([
        "--format", "|sv||1|2|3|4|5",
        "--reporter-url", "local",
        "--privacy", "1",
        "--quantisation", "3600",
        "--source", "test",
        "--output", str(out_dir),
        "--flush-interval", "1",
    ])
    assert rc == 0
